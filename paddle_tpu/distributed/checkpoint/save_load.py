"""Sharded save/load with cross-topology reshard-on-load
(parity: distributed/checkpoint/{save_state_dict,load_state_dict}.py).

Works for single-process multi-device (all shards addressable) and
multi-process: each process writes its addressable shards plus a per-rank
metadata piece; after a global barrier the coordinator merges the pieces
into the global ``metadata.pkl`` (the file-based analogue of the reference's
NCCL-coordinated gather/dedup in save_state_dict.py).

Commit protocol (see RESILIENCE.md): every save stages into
``<path>.tmp/``; per-shard SHA-256 checksums are recorded in the metadata;
only after the post-barrier metadata merge does the coordinator write a
``COMMIT`` marker and rename the staging dir to ``<path>``. A crash at any
earlier point leaves a ``*.tmp`` dir that ``is_committed`` (and
``ElasticManager.latest_checkpoint``) rejects, so a resume can never pick
up a torn checkpoint. ``load_state_dict`` re-verifies checksums and raises
:class:`CheckpointCorruptionError` naming the damaged shard.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import shutil

import jax
import numpy as np

from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata
from .. import fault
from ..watchdog import watch

__all__ = ["save_state_dict", "load_state_dict", "AsyncSaveHandle",
           "CheckpointCorruptionError", "is_committed",
           "drain_inflight_saves", "COMMIT_MARKER"]

COMMIT_MARKER = "COMMIT"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification: a shard's bytes do not
    match the checksum recorded at save time, a shard file is unreadable,
    or the directory was never committed (torn mid-save)."""


def _staging(path: str) -> str:
    return path.rstrip("/\\") + ".tmp"


def is_committed(path: str) -> bool:
    """True iff ``path`` is a committed checkpoint: a directory carrying the
    ``COMMIT`` marker (or, for checkpoints written before the commit
    protocol existed, a merged ``metadata.pkl``) and not a ``*.tmp``
    staging dir. Non-directory paths (single-file checkpoints) are outside
    the protocol and count as committed by existing."""
    if not os.path.isdir(path):
        return os.path.exists(path)
    if os.path.normpath(path).endswith(".tmp"):
        return False
    return (os.path.isfile(os.path.join(path, COMMIT_MARKER))
            or os.path.isfile(os.path.join(path, "metadata.pkl")))


def _checksum(data: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(data).tobytes()).hexdigest()


def _shards_of(arr: jax.Array):
    """Yield (global_offset, numpy_data) for each addressable, deduped shard."""
    seen = set()
    if not isinstance(arr, jax.Array):
        arr = jax.numpy.asarray(arr)
    for shard in arr.addressable_shards:
        idx = shard.index  # tuple of slices
        offset = tuple(0 if s.start is None else int(s.start) for s in idx)
        if offset in seen:
            continue  # replicated copy
        seen.add(offset)
        yield offset, np.asarray(shard.data)


def _barrier(tag: str) -> None:
    fault.trip("ckpt.barrier")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # watchdog escalation: a rank that died mid-save leaves everyone
        # else parked here forever — the watchdog turns that silent hang
        # into a diagnosed abort the launcher can gang-restart
        with watch("ckpt.barrier", tag=tag):
            multihost_utils.sync_global_devices(tag)


class AsyncSaveHandle:
    """Handle for an in-flight async checkpoint save (orbax-style async —
    the SURVEY §7 target for the distributed-checkpoint row). The device
    arrays are snapshotted to host (per shard) BEFORE the background thread
    starts, so training can mutate (donate) them immediately."""

    def __init__(self, thread, err_cell):
        self._thread = thread
        self._err = err_cell

    def result(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("async checkpoint save still running")
        if self._err[0] is not None:
            raise self._err[0]

    wait = result

    def done(self) -> bool:
        """True once the background write finished; raises the background
        error (failed saves must not read as completed)."""
        if self._thread.is_alive():
            return False
        if self._err[0] is not None:
            raise self._err[0]
        return True


def _build_rank_payload(state_dict: dict, fname: str):
    """Device→host per-shard extraction (shared by sync and async paths:
    async runs this on the MAIN thread so only file IO goes background,
    preserving the sharded file layout and per-shard host copies)."""
    meta = Metadata()
    payload = {}
    for key, arr in state_dict.items():
        if arr is None:
            continue
        if not isinstance(arr, jax.Array):
            arr = jax.numpy.asarray(arr)
        meta.global_shapes[key] = tuple(arr.shape)
        shard_metas = []
        for offset, data in _shards_of(arr):
            lm = LocalTensorMetadata(offset, tuple(data.shape), str(data.dtype))
            shard_metas.append(lm)
            li = LocalTensorIndex(key, offset)
            meta.storage_metadata[li] = fname
            payload[f"{key}|{','.join(map(str, offset))}"] = np.asarray(data)
        meta.state_dict_metadata[key] = shard_metas
    return meta, payload


def _write_rank_files(path: str, rank: int, meta, payload) -> None:
    # checksums are taken from the exact host buffers being written, in the
    # writer (possibly background) thread, so hashing overlaps training
    for pk, data in payload.items():
        meta.checksums[pk] = _checksum(data)
    npz_path = os.path.join(path, f"{rank}.distcp.npz")
    np.savez(npz_path, **payload)
    fault.trip("ckpt.write_shard", rank=rank, path=npz_path)
    with open(os.path.join(path, f"{rank}.meta.pkl"), "wb") as f:
        pickle.dump(meta, f)


def _merge_metadata(path: str, nprocs: int, seq: int | None = None) -> None:
    """Coordinator: merge per-rank metadata pieces into the global
    ``metadata.pkl`` (written atomically via rename so a reader never
    sees a partial file), then clean the pieces up — removing the done
    markers LAST, since non-coordinator async ranks treat their marker's
    disappearance as 'merge published'."""
    merged = Metadata()
    for r in range(nprocs):
        piece_path = os.path.join(path, f"{r}.meta.pkl")
        if not os.path.exists(piece_path):
            raise FileNotFoundError(
                f"checkpoint merge: rank {r}'s metadata piece missing under "
                f"{path!r}. In a multi-host job this usually means the "
                f"checkpoint path does not resolve to one shared directory "
                f"on every rank (e.g. a relative path with per-rank cwds).")
        with open(piece_path, "rb") as f:
            piece: Metadata = pickle.load(f)
        merged.global_shapes.update(piece.global_shapes)
        for li, file in piece.storage_metadata.items():
            # replicated shards may be written by several ranks; first wins
            merged.storage_metadata.setdefault(li, file)
        for pk, digest in getattr(piece, "checksums", {}).items():
            # replicated copies hold identical bytes, so first-wins here
            # stays consistent with whichever file storage_metadata kept
            merged.checksums.setdefault(pk, digest)
        for key, shard_metas in piece.state_dict_metadata.items():
            have = {sm.global_offset
                    for sm in merged.state_dict_metadata.get(key, [])}
            merged.state_dict_metadata.setdefault(key, []).extend(
                sm for sm in shard_metas if sm.global_offset not in have)
    tmp = os.path.join(path, "metadata.pkl.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(merged, f)
    os.replace(tmp, os.path.join(path, "metadata.pkl"))
    for r in range(nprocs):
        os.remove(os.path.join(path, f"{r}.meta.pkl"))
    if seq is not None:
        for r in range(nprocs):
            done = os.path.join(path, _done_name(r, seq))
            if os.path.exists(done):
                os.remove(done)


def _commit(stage: str, final: str) -> None:
    """Coordinator-only atomic publish: write the COMMIT marker into the
    staging dir, then rename it into place. Everything before the rename is
    crash-safe (a torn ``*.tmp`` is skipped by readers); overwriting an
    existing committed checkpoint swaps via ``<final>.old`` so a committed
    dir exists at the target for all but the instant between renames."""
    fault.trip("ckpt.commit", path=final)
    with open(os.path.join(stage, COMMIT_MARKER), "w") as f:
        f.write(f"nprocs={jax.process_count()}\n")
    if os.path.isdir(final):
        old = final + ".old"
        if os.path.isdir(old):
            shutil.rmtree(old)
        os.rename(final, old)
        os.rename(stage, final)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(stage, final)


# per-path async save sequence: every rank of an SPMD program calls save
# the same number of times, so the counter is a shared round id without
# any cross-process coordination — markers from an earlier round (or a
# previous timed-out attempt within this process) can never satisfy this
# round's wait. Cross-RESTART staleness is handled by each rank clearing
# its own old markers on entry; jobs that crash mid-save should resume
# into a fresh step directory (the ElasticManager step_N convention).
_SAVE_SEQ: dict[str, int] = {}
# in-flight async handles per path: a second async save to the same path
# must not start while the previous round's markers are still live (its
# entry cleanup would eat them), so save_state_dict awaits the prior
# handle first (cheap: the write is usually done by the next save call)
_INFLIGHT: dict[str, "AsyncSaveHandle"] = {}


def _done_name(rank: int, seq: int) -> str:
    return f"{rank}.done.{seq}"


def drain_inflight_saves(timeout: float = 600.0) -> list:
    """Join every in-flight async save (the preemption path: a SIGTERMed
    trainer must not die with a checkpoint half-written). Returns
    ``[(path, exception), ...]`` for saves that failed or timed out instead
    of raising — the caller is usually about to take a final synchronous
    checkpoint and should not be derailed by an already-doomed async one."""
    errs = []
    for p, h in list(_INFLIGHT.items()):
        try:
            h.result(timeout=timeout)
        except BaseException as e:  # noqa: BLE001 — collected, not fatal
            errs.append((p, e))
    return errs


def _wait_marker(predicate, what: str, timeout: float) -> None:
    import time
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"async checkpoint: timed out after {timeout}s waiting for "
                f"{what}")
        time.sleep(0.02)


def save_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False,
                    async_timeout: float = 600.0):
    """Write a sharded checkpoint. With ``async_save=True``, device→host
    shard transfer happens now but file IO + metadata merge run in a
    background thread; returns an AsyncSaveHandle (call .result() before
    relying on the files). Multi-process async coordinates through done-
    marker files polled by the coordinator's writer thread — no device
    collectives off the main thread.

    Multi-host contract: every rank must pass the SAME path string (after
    normpath) naming ONE shared directory. The cross-rank barrier tag is
    derived from that string — not from abspath, whose per-host cwd would
    desynchronize ranks launched from different directories. Mixed
    spellings (absolute on one rank, relative on another) fail loudly at
    the barrier's name check; same string but different resolved
    directories fail loudly at merge time.

    Atomicity: all ranks write into the ``<path>.tmp/`` staging dir; the
    coordinator commits (COMMIT marker + rename to ``path``) only after the
    post-barrier metadata merge. A crash anywhere mid-save leaves only the
    torn staging dir, never a half-written ``path``."""
    # barrier tag: normalized but NOT absolutized — ranks on different hosts
    # may run with different cwds yet pass the same relative path, and the
    # tag must be byte-identical on every rank (abspath/realpath would fold
    # in per-host cwd / symlink state)
    tag = os.path.normpath(path)
    # local canonical key: two spellings of one directory ('ck' vs './ck' vs
    # absolute) must share the in-flight guard and the round counter; this
    # key is process-local so absolutizing is safe here
    path = os.path.abspath(path)
    stage = _staging(path)
    os.makedirs(stage, exist_ok=True)
    rank = jax.process_index()
    nprocs = jax.process_count()
    # an in-flight async save to the same path must finish before ANY new
    # save (sync or async) touches its files
    prev = _INFLIGHT.get(path)
    if prev is not None:
        try:
            prev.result(timeout=async_timeout)
        except TimeoutError:
            raise
        except Exception:  # noqa: BLE001 — surfaced via prev's handle
            pass
    meta, payload = _build_rank_payload(state_dict, f"{rank}.distcp.npz")
    if async_save:
        import glob
        import threading
        seq = _SAVE_SEQ[path] = _SAVE_SEQ.get(path, 0) + 1
        # clear ALL of this rank's markers (leftovers of a previous process
        # restarted into the same dir, or of a timed-out round) so none can
        # masquerade as this round's; work() recreates ours after the write.
        # glob.escape: metacharacters in the checkpoint path (step_[1]/)
        # must not silently match nothing and leave stale markers behind
        for stale in glob.glob(os.path.join(glob.escape(stage),
                                            _done_name(rank, "*"))):
            os.remove(stale)
        err_cell = [None]

        def work():
            try:
                _write_rank_files(stage, rank, meta, payload)
                mine = os.path.join(stage, _done_name(rank, seq))
                with open(mine, "w"):
                    pass
                if rank == coordinator_rank:
                    with watch("ckpt.async_merge_wait", path=path, seq=seq):
                        _wait_marker(
                            lambda: all(os.path.exists(
                                os.path.join(stage, _done_name(r, seq)))
                                for r in range(nprocs)),
                            f"all ranks' round-{seq} markers under "
                            f"{stage!r}", async_timeout)
                    _merge_metadata(stage, nprocs, seq=seq)
                    _commit(stage, path)
                elif nprocs > 1:
                    # merge consumed my marker AND the COMMIT marker exists
                    # at the final path => the staging dir was renamed into
                    # place; makes .result() mean 'checkpoint committed and
                    # readable' on every rank
                    commit_path = os.path.join(path, COMMIT_MARKER)
                    with watch("ckpt.async_commit_wait", path=path, seq=seq):
                        _wait_marker(
                            lambda: (not os.path.exists(mine)
                                     and os.path.isfile(commit_path)),
                            f"coordinator commit of round {seq} at "
                            f"{path!r}", async_timeout)
            except BaseException as e:  # noqa: BLE001
                err_cell[0] = e

        # non-daemon: interpreter exit joins the writer, so a script that
        # forgets handle.result() still gets a complete checkpoint instead
        # of a silently truncated one
        t = threading.Thread(target=work, daemon=False)
        handle = AsyncSaveHandle(t, err_cell)
        _INFLIGHT[path] = handle
        t.start()
        return handle
    _write_rank_files(stage, rank, meta, payload)
    _barrier(f"ckpt_save_shards:{tag}")
    if rank == coordinator_rank:
        _merge_metadata(stage, nprocs)
        _commit(stage, path)
    _barrier(f"ckpt_save_meta:{tag}")


def _overlap(dst_off, dst_shape, src_off, src_shape):
    """Intersection of two boxes; returns (dst_slices, src_slices) or None."""
    dst_sl, src_sl = [], []
    for do, ds, so, ss in zip(dst_off, dst_shape, src_off, src_shape):
        lo = max(do, so)
        hi = min(do + ds, so + ss)
        if lo >= hi:
            return None
        dst_sl.append(slice(lo - do, hi - do))
        src_sl.append(slice(lo - so, hi - so))
    return tuple(dst_sl), tuple(src_sl)


def load_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0) -> dict:
    """Fill ``state_dict``'s arrays (templates carrying target sharding) from
    a checkpoint saved under any topology; returns the new dict. Every shard
    read is verified against the SHA-256 recorded at save time; a mismatch
    (bit flip, torn write) raises :class:`CheckpointCorruptionError` naming
    the shard."""
    meta_path = os.path.join(path, "metadata.pkl")
    if not os.path.isfile(meta_path):
        raise CheckpointCorruptionError(
            f"checkpoint at {path!r} has no metadata.pkl — it is torn or "
            f"was never committed (a crash mid-save leaves a '*.tmp' "
            f"staging dir; resume from the newest COMMITTED checkpoint, "
            f"see RESILIENCE.md)")
    with open(meta_path, "rb") as f:
        meta: Metadata = pickle.load(f)
    checksums: dict = getattr(meta, "checksums", None) or {}
    verified: set = set()
    # lazy-load shard files
    files: dict[str, np.lib.npyio.NpzFile] = {}

    def get_payload(fname, key, offset):
        pk = f"{key}|{','.join(map(str, offset))}"
        import zipfile
        try:
            if fname not in files:
                files[fname] = np.load(os.path.join(path, fname))
            data = files[fname][pk]
        except (OSError, KeyError, ValueError, zipfile.BadZipFile) as e:
            # zipfile CRC errors / truncated archives / missing entries —
            # the shard file itself is damaged
            raise CheckpointCorruptionError(
                f"checkpoint shard {pk!r} in {fname!r} under {path!r} is "
                f"unreadable ({type(e).__name__}: {e})") from e
        want = checksums.get(pk)
        if want is not None and pk not in verified:
            got = _checksum(data)
            if got != want:
                raise CheckpointCorruptionError(
                    f"checkpoint shard {pk!r} in {fname!r} under {path!r} "
                    f"failed checksum verification (recorded sha256 "
                    f"{want[:16]}…, got {got[:16]}…) — the file was "
                    f"corrupted after it was written")
            verified.add(pk)
        return data

    out = {}
    for key, target in state_dict.items():
        if key not in meta.state_dict_metadata:
            out[key] = target
            continue
        if not isinstance(target, jax.Array):
            target = jax.numpy.asarray(target)
        sharding = target.sharding
        saved = meta.state_dict_metadata[key]

        def make_local(index):
            dst_off = tuple(0 if s.start is None else int(s.start) for s in index)
            dst_shape = tuple(
                (s.stop if s.stop is not None else g) - (s.start or 0)
                for s, g in zip(index, target.shape)) if index else target.shape
            buf = np.zeros(dst_shape, target.dtype)
            covered = np.zeros(dst_shape, bool)
            for sm in saved:
                ov = _overlap(dst_off, dst_shape, sm.global_offset, sm.local_shape)
                if ov is None:
                    continue
                dst_sl, src_sl = ov
                data = get_payload(
                    meta.storage_metadata[LocalTensorIndex(key, sm.global_offset)],
                    key, sm.global_offset)
                buf[dst_sl] = data[src_sl]
                covered[dst_sl] = True
            if not covered.all():
                raise ValueError(
                    f"checkpoint at {path!r} does not cover tensor {key!r}: "
                    f"region offset={dst_off} shape={dst_shape} has "
                    f"{int((~covered).sum())} uncovered elements (saved shards "
                    f"are incomplete for this target sharding)")
            return buf

        if target.ndim == 0:
            arr = jax.device_put(get_payload(
                meta.storage_metadata[LocalTensorIndex(key, ())], key, ()), sharding)
        else:
            arr = jax.make_array_from_callback(target.shape, sharding, make_local)
        out[key] = arr
    for f in files.values():
        f.close()
    return out
