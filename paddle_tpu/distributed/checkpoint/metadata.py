"""Checkpoint metadata (parity: distributed/checkpoint/metadata.py:20-40)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class LocalTensorMetadata:
    """One shard: where it sits in the global tensor."""

    global_offset: tuple
    local_shape: tuple
    dtype: str


@dataclass(frozen=True)
class LocalTensorIndex:
    """Key of a shard: (tensor name, global offset)."""

    tensor_key: str
    global_offset: tuple


@dataclass
class Metadata:
    # tensor name -> list of shard metadata
    state_dict_metadata: dict = field(default_factory=dict)
    # LocalTensorIndex -> file name
    storage_metadata: dict = field(default_factory=dict)
    # tensor name -> global shape
    global_shapes: dict = field(default_factory=dict)
    # "tensor|offset" payload key -> SHA-256 hexdigest of the shard's raw
    # bytes, recorded at write time and re-verified on load so a torn or
    # bit-flipped shard fails loudly instead of poisoning a resume.
    # (Metadata pickled before this field existed lacks the attribute —
    # readers use getattr(meta, "checksums", {}).)
    checksums: dict = field(default_factory=dict)
