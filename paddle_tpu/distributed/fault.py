"""Deterministic fault-injection harness for chaos-testing the runtime.

Production fault paths (torn checkpoints, hung collectives, preempted or
SIGKILLed ranks) are untestable if faults fire at random. A
:class:`FaultPlan` is a list of :class:`FaultSpec` entries keyed by
``(rank, step, site)`` — a fault fires iff the process's rank, the current
training step, and the named code site all match, so a chaos test replays
the exact same failure every run. Probabilistic specs draw from a hash of
``(seed, rank, step, site)``, never from wall-clock entropy, so even
"random" chaos is reproducible.

Named sites wired into the runtime (see RESILIENCE.md):

- ``train.step``       — tripped by training loops that opt in
  (``fault.trip("train.step")`` once per step, after ``fault.set_step(i)``)
- ``ckpt.write_shard`` — inside the per-rank shard write (ctx: ``path`` of
  the npz just written, so ``torn``/``corrupt`` can damage it)
- ``ckpt.commit``      — in the coordinator immediately before the staging
  dir is renamed into place
- ``ckpt.barrier``     — the cross-rank checkpoint barrier
- ``collective.barrier`` — the eager collective barrier
- ``serving.step`` / ``serving.prefill`` / ``serving.decode`` /
  ``serving.alloc`` — the serving engine's per-step, per-request and
  page-allocation sites (SERVING.md "Serving failure modes"); the
  per-request sites pass the request id as ``ctx['path']`` so ``match``
  pins a fault to ONE request (``serving.alloc`` passes the fleet
  replica index when a router owns the pool, so ``match`` can pin an
  alloc storm to one replica).
- ``serving.spill`` / ``serving.restore`` — the KV host-tier demotion /
  promotion sites (SERVING.md "KV tiering & traffic harness").
  ``ctx['path']`` is the page's content-hash key (hex). ``raise`` drops
  the spill (page lost, as without a tier) or fails the restore (those
  tokens recompute); ``poison`` corrupts the stored host payload
  WITHOUT updating its digest, so the restore-side blake2b re-verify
  must detect it and fall back to recompute — wrong KV is never served.
- ``serving.snapshot`` / ``serving.snapshot_restore`` — the crash-
  consistent snapshot capture / restore sites (serving/snapshot.py;
  RESILIENCE.md "Serving recovery playbook"). ``ctx['path']`` is the
  request id. ``raise`` at capture drops that request's snapshot (the
  previous capture, or full replay, covers it); ``raise`` at restore
  falls the failover back to full replay. ``poison`` corrupts the
  stored / about-to-be-injected payload WITHOUT updating its blake2b
  digests, so the restore-side re-verify must catch it and recompute —
  a poisoned snapshot can cost time, never correctness.
- ``serving.admission`` / ``serving.brownout`` — the overload-control
  sites (SERVING.md "Overload control & tenant fairness").
  ``serving.admission`` fires in ``add_request`` after the request id
  is fixed but before any quota/queue state changes (``ctx['path']``
  is the request id); ``raise`` models the admission path itself
  crashing — the fleet router counts it as a breaker failure and the
  record stays queued. ``serving.brownout`` fires at every brownout
  ladder transition, AFTER the new level is committed
  (``ctx['path']`` is ``"old->new"``, e.g. ``"1->2"``); ``raise``
  models the overload controller dying mid-transition — the step
  aborts but the ladder state stays consistent.
- ``fleet.dispatch`` / ``fleet.replica_kill`` / ``fleet.health`` — the
  serving fleet router's placement, replica-life and health-probe sites
  (SERVING.md "Engine fleet & failover"). ``ctx['path']`` is the request
  id for ``fleet.dispatch`` and the replica index for the other two, so
  ``match=r"^1$"`` chaos-kills exactly replica 1; ``step`` is the
  router's step counter.
- ``fleet.transport.send`` / ``fleet.transport.recv`` — the fleet
  transport's per-message sites (SERVING.md "Fleet transport &
  membership"), fired for EVERY router<->replica message at send and at
  delivery. ``ctx['path']`` is ``"<KIND>:<rid>"`` (e.g.
  ``"SUBMIT:fleet-req-3"``), so ``match`` pins a fault to one message
  kind of one request. They support the transport actions ``drop``
  (message vanishes), ``dup`` (delivered twice — receiver dedup must
  collapse it), ``delay`` (``arg`` = router steps on the injectable
  clock) and ``corrupt`` (flip one payload byte WITHOUT updating the
  digest — the receive-side blake2b re-verify must catch it); ``step``
  is the router's step counter.
- ``fleet.transport.connect`` / ``fleet.transport.accept`` — the
  multi-host socket transport's connection-life sites
  (serving/transport_socket.py; SERVING.md "Multi-host serving" and
  RESILIENCE.md "Multi-host playbook"), fired per dial attempt and per
  accepted connection. ``ctx['path']`` is the dialed peer's name
  (``"router"``) on connect and the connector's ``"ip:port"`` on
  accept. ``drop`` swallows the attempt (the dialer backs off and
  retries; an accepted-then-dropped connector sees a silent EOF),
  ``delay`` (``arg`` = SECONDS — wall time, because sockets are)
  parks it, and ``raise`` models a refused/RST connection — there is
  no distinct "reset" action; ``raise`` at these sites IS the reset,
  counted as ``socket_resets``. Armed via ``PADDLE_FAULT_PLAN`` they
  replay the same connection storm in every spawned replica host.

Actions: ``hang`` (sleep ``arg`` seconds — trips the comm watchdog),
``kill`` (SIGKILL self: the un-catchable death), ``exit`` (``os._exit(arg)``),
``raise`` (raise :class:`FaultInjected`), ``torn`` (truncate the file in
``ctx['path']`` to half its size — a torn write), ``corrupt`` (flip one
byte mid-file, or invoke the site's ``ctx['corrupt']`` callback when one
is passed — the fleet transport corrupts in-memory wire bytes, not
files), ``poison`` (invoke the site's ``ctx['poison']`` callback —
serving sites pass one that writes NaN into the request's KV pages, the
device-buffer analogue of ``corrupt``), ``drop`` / ``dup`` / ``delay``
(invoke the site's same-named callbacks — message-transport faults; a
site that passes no such callback raises :class:`FaultInjected`).

Activation: programmatically via :func:`activate`, or across process
boundaries via the ``PADDLE_FAULT_PLAN`` env var holding
``FaultPlan.to_json()`` — the launcher's workers inherit it, which is how
a chaos test arms a fault inside a gang it spawns.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import signal
import threading
import time
from dataclasses import asdict, dataclass

__all__ = ["FaultSpec", "FaultPlan", "FaultInjected", "activate",
           "deactivate", "active_plan", "trip", "set_step", "current_step"]

ENV_VAR = "PADDLE_FAULT_PLAN"


class FaultInjected(RuntimeError):
    """Raised by the ``raise`` action — a synthetic, identifiable failure."""


@dataclass
class FaultSpec:
    site: str                  # named code site this spec arms
    action: str                # hang | kill | exit | raise | torn | corrupt
    rank: int | None = None    # None = any rank
    step: int | None = None    # None = any step
    epoch: int | None = None   # restart epoch (None = any) — lets a plan
    #                            fire only on the first life of a gang
    prob: float = 1.0          # <1.0: deterministic hash draw, not random()
    arg: float | None = None   # hang seconds / exit code
    once: bool = True          # fire at most once per process
    nth: int | None = None     # fire on the Nth matching visit (1-based) —
    #                            targets e.g. "the 4th commit" exactly even
    #                            when the site runs on a background thread
    #                            whose step context is ambiguous
    match: str | None = None   # regex the site's ctx['path'] must contain —
    #                            pins a fault to ONE file/checkpoint (e.g.
    #                            r"step_3$") independent of thread timing

    def __post_init__(self):
        if self.action not in ("hang", "kill", "exit", "raise", "torn",
                               "corrupt", "poison", "drop", "dup", "delay"):
            raise ValueError(f"unknown fault action {self.action!r}")


def _env_int(*names: str) -> int:
    for n in names:
        v = os.environ.get(n)
        if v:
            return int(v)
    return 0


class FaultPlan:
    """An armed set of :class:`FaultSpec` entries with deterministic draws."""

    def __init__(self, specs=(), seed: int = 0):
        self.specs = [s if isinstance(s, FaultSpec) else FaultSpec(**s)
                      for s in specs]
        self.seed = int(seed)
        self._fired: set[int] = set()
        self._visits: dict[int, int] = {}
        self._lock = threading.Lock()

    # -- (de)serialization: the env-var transport for launcher-spawned gangs
    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "specs": [asdict(s) for s in self.specs]})

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        obj = json.loads(text)
        return cls(obj.get("specs", ()), seed=obj.get("seed", 0))

    # -- matching
    def _draw(self, spec: FaultSpec, rank: int, step: int | None) -> bool:
        if spec.prob >= 1.0:
            return True
        h = hashlib.sha256(
            f"{self.seed}:{rank}:{step}:{spec.site}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2**64 < spec.prob

    def trip(self, site: str, *, step: int | None = None,
             rank: int | None = None, **ctx) -> None:
        if not self.specs:
            return
        if rank is None:
            rank = _env_int("PADDLE_TRAINER_ID", "PROCESS_ID")
        if step is None:
            step = current_step()
        epoch = _env_int("PADDLE_RESTART_EPOCH")
        for i, spec in enumerate(self.specs):
            if spec.site != site:
                continue
            if spec.rank is not None and spec.rank != rank:
                continue
            if spec.step is not None and spec.step != step:
                continue
            if spec.epoch is not None and spec.epoch != epoch:
                continue
            if spec.match is not None and not re.search(
                    spec.match, str(ctx.get("path") or "")):
                continue
            with self._lock:
                if spec.once and i in self._fired:
                    continue
                visit = self._visits[i] = self._visits.get(i, 0) + 1
                if spec.nth is not None and visit != spec.nth:
                    continue
                if not self._draw(spec, rank, step):
                    continue
                self._fired.add(i)
            self._fire(spec, site, ctx)

    # -- actions
    def _fire(self, spec: FaultSpec, site: str, ctx: dict) -> None:
        tag = (f"[fault] {spec.action} @ {site} "
               f"(rank={spec.rank} step={spec.step})")
        if spec.action == "hang":
            time.sleep(float(spec.arg if spec.arg is not None else 3600.0))
        elif spec.action == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif spec.action == "exit":
            os._exit(int(spec.arg if spec.arg is not None else 1))
        elif spec.action == "raise":
            raise FaultInjected(tag)
        elif spec.action == "poison":
            fn = ctx.get("poison")
            if fn is None:
                raise FaultInjected(f"{tag}: site passed no poison callback")
            fn()
        elif spec.action in ("drop", "dup", "delay"):
            fn = ctx.get(spec.action)
            if fn is None:
                raise FaultInjected(
                    f"{tag}: site passed no {spec.action} callback")
            if spec.action == "delay":
                fn(spec.arg if spec.arg is not None else 1)
            else:
                fn()
        elif spec.action == "corrupt" and callable(ctx.get("corrupt")):
            # message-transport sites corrupt in-memory wire bytes via a
            # callback; file-based corruption below stays the default
            ctx["corrupt"]()
        elif spec.action in ("torn", "corrupt"):
            path = ctx.get("path")
            if not path or not os.path.exists(path):
                raise FaultInjected(f"{tag}: site passed no file to damage")
            size = os.path.getsize(path)
            if spec.action == "torn":
                with open(path, "r+b") as f:
                    f.truncate(max(1, size // 2))
            else:
                with open(path, "r+b") as f:
                    f.seek(size // 2)
                    b = f.read(1)
                    f.seek(size // 2)
                    f.write(bytes([b[0] ^ 0xFF]))


# --- process-global plan + step cursor ------------------------------------

_active: list[FaultPlan | None] = [None]
_env_checked = [False]
# process-global, NOT thread-local: checkpoint writer threads must see the
# training loop's step cursor (a bg thread has no step context of its own)
_step: list[int | None] = [None]


def activate(plan: FaultPlan) -> FaultPlan:
    _active[0] = plan
    _env_checked[0] = True  # explicit plan overrides the env transport
    return plan


def deactivate() -> None:
    _active[0] = None
    _env_checked[0] = True


def active_plan() -> FaultPlan | None:
    if _active[0] is None and not _env_checked[0]:
        _env_checked[0] = True
        raw = os.environ.get(ENV_VAR)
        if raw:
            _active[0] = FaultPlan.from_json(raw)
    return _active[0]


def set_step(step: int) -> None:
    """Advance the harness's step cursor (training loops call this once per
    step so sites deep in library code — shard writes, barriers — can match
    ``step``-keyed specs without threading the step through every call).
    Background writer threads read the cursor too, which makes step-keyed
    specs racy against async saves — key those on ``nth`` instead."""
    _step[0] = int(step)


def current_step() -> int | None:
    return _step[0]


def trip(site: str, *, step: int | None = None, rank: int | None = None,
         **ctx) -> None:
    """Library hook: fire any armed fault matching this site. No-op (one
    attribute read) when no plan is active — safe on hot-ish paths."""
    plan = active_plan()
    if plan is not None:
        plan.trip(site, step=step, rank=rank, **ctx)
