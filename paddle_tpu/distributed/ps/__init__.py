"""Parameter-server training — DEPRIORITIZATION NOTE (SURVEY §A.2, §2.3
rows "Parameter server", "PS (python)", "transpiler").

The reference ships an industrial async-PS stack (~35k LoC C++:
fluid/distributed/ps/ brpc services + dense/sparse tables + SSD-backed
embeddings, plus the fluid/framework C++ trainer/DataFeed hierarchy and the
legacy Python DistributeTranspiler). That stack exists to serve
**sparse-recommendation workloads on CPU clusters**: hundred-billion-row
embedding tables sharded across parameter servers, updated asynchronously
by Hogwild-style trainers.

Decision: NOT rebuilt for the TPU framework, deliberately.

1. **Hardware mismatch.** The PS architecture exists because commodity CPU
   clusters have no fast collective fabric; TPU slices have ICI. Dense
   training that the reference runs over PS is strictly better expressed
   here as data/FSDP parallelism over the mesh (distributed/sharding.py).
2. **The sparse path has a different TPU-native answer.** Giant embedding
   tables on TPU use SparseCore/embedding-partitioning via GSPMD sharded
   `nn.Embedding` (vocab-sharded on mp/fsdp axes — already supported), or
   host-RAM lookups feeding the device via the input pipeline. An
   async-PS rebuild would be slower than either.
3. **Deprecated upstream.** The fluid transpiler path is legacy in the
   reference itself (superseded by fleet collective mode).

What IS provided for the workloads PS served:
- vocab-sharded `VocabParallelEmbedding` (fleet/mp_layers.py) for large
  embedding tables under collective training;
- distributed checkpoint with reshard-on-load for huge model state;
- the launch/elastic stack for multi-host orchestration.

Importing the symbols below raises with this explanation.
"""

from __future__ import annotations

__all__ = ["DistributedTranspiler", "fleet_ps_mode"]

_MSG = ("parameter-server training is deliberately not implemented in the "
        "TPU framework: use collective (dp/fsdp/mp) training over the mesh; "
        "see paddle_tpu/distributed/ps/__init__.py for the full rationale")


class DistributedTranspiler:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


def fleet_ps_mode(*a, **k):
    raise NotImplementedError(_MSG)
