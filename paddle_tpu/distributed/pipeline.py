"""Pipeline parallelism (parity: fleet/meta_parallel/ — PipelineLayer
pp_layers.py:257, 1F1B scheduler pipeline_parallel.py:148/455, p2p handoff
p2p_communication.py:559; behavioral spec SURVEY §B.1).

TPU-native architecture: no per-rank interpreter or message bus. The whole
pipeline is ONE SPMD program under shard_map over the 'pp' mesh axis:

- homogeneous stage layers are STACKED — params get a leading layer axis
  sharded on pp (each device owns L/P layers, applied with lax.scan);
- the microbatch schedule is a lax.scan over T = M + P - 1 ticks; at tick t
  stage r computes microbatch t - r, then hands its activation to stage r+1
  with a single ring ppermute (the p2p send/recv pair);
- reverse pass: jax.grad differentiates through scan + ppermute, yielding
  the mirrored backward pipeline automatically (GPipe fill-drain schedule;
  activation memory bounded by remat of the stage body).

The reference's 1F1B ordering reduces peak activation memory vs fill-drain;
under remat the difference is one stage's activations per in-flight
microbatch — acceptable for round 1 and marked for the scheduler upgrade.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..core import mesh as mesh_lib
from ..nn.module import Layer, functional_call

__all__ = ["pipeline_forward", "stack_layer_params", "PipelineStagedLayers"]


def stack_layer_params(layers: Sequence[Layer]) -> dict[str, jax.Array]:
    """Stack the path-keyed params of homogeneous layers along a new leading
    axis: list of L layers -> {path: [L, ...]} (the PipelineLayer
    LayerDesc-list collapses into one stacked tensor per weight)."""
    dicts = [l.state_dict(include_non_persistable_buffer=True) for l in layers]
    keys = dicts[0].keys()
    for d in dicts[1:]:
        if d.keys() != keys:
            raise ValueError("pipeline stages must be homogeneous")
    return {k: jnp.stack([d[k] for d in dicts]) for k in keys}


def pipeline_forward(stacked: dict[str, jax.Array], x: jax.Array,
                     layer_apply: Callable, *, mesh: Mesh | None = None,
                     axis: str = "pp", num_micro: int = 1,
                     remat: bool = True) -> jax.Array:
    """Run x through L stacked layers pipelined over the pp axis.

    stacked: {path: [L, ...]} (sharded or not — shard_map partitions by spec)
    x: [batch, ...] global batch; split into num_micro microbatches.
    layer_apply(params_slice, h) -> h : applies ONE layer.
    """
    mesh = mesh or mesh_lib.current_mesh()
    pp = mesh_lib.axis_size(axis, mesh) if mesh else 1
    if mesh is None or pp == 1:
        def body(h, sl):
            return layer_apply(sl, h), None
        out, _ = lax.scan(body, x, stacked)
        return out
    if x.shape[0] % num_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by {num_micro} microbatches")
    mb = x.shape[0] // num_micro
    xs = x.reshape(num_micro, mb, *x.shape[1:])

    apply_one = jax.checkpoint(layer_apply) if remat else layer_apply

    def stage_fn(local_params, h):
        # local_params leaves: [L/P, ...]; scan them over the microbatch act
        def body(carry, sl):
            return apply_one(sl, carry), None
        out, _ = lax.scan(body, h, local_params)
        return out

    T = num_micro + pp - 1
    perm_fwd = [(r, (r + 1) % pp) for r in range(pp)]

    def per_device(local_params, xs_local):
        r = lax.axis_index(axis)
        h0 = jnp.zeros((mb,) + xs_local.shape[2:], xs_local.dtype)
        outs0 = jnp.zeros_like(xs_local)

        def tick(carry, t):
            h_in, outs = carry
            m_idx = t - r  # microbatch this stage handles at tick t
            valid = (m_idx >= 0) & (m_idx < num_micro)
            # stage 0 reads from the input queue; others use the received act
            src = lax.cond(r == 0,
                           lambda _: lax.dynamic_index_in_dim(
                               xs_local, jnp.clip(m_idx, 0, num_micro - 1), 0,
                               keepdims=False),
                           lambda _: h_in, None)
            y = stage_fn(local_params, src)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            # last stage banks its finished microbatch
            outs = lax.cond(
                (r == pp - 1) & valid,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(m_idx, 0, num_micro - 1), 0),
                lambda o: o, outs)
            # hand off to the next stage (ring; stage P-1 -> 0 is ignored)
            h_next = lax.ppermute(y, axis, perm_fwd)
            return (h_next, outs), None

        (_, outs), _ = lax.scan(tick, (h0, outs0), jnp.arange(T))
        # broadcast final outputs from the last stage to every rank
        outs = lax.psum(jnp.where(r == pp - 1, outs, jnp.zeros_like(outs)), axis)
        return outs

    pspec = jax.tree.map(lambda v: P(axis, *([None] * (v.ndim - 1))), stacked)
    out = shard_map(per_device, mesh=mesh,
                    in_specs=(pspec, P()), out_specs=P(),
                    check_vma=False)(stacked, xs)
    return out.reshape(x.shape[0], *out.shape[2:])


class PipelineStagedLayers(Layer):
    """Module owning stacked homogeneous layers, executed pipelined.

    Parity: PipelineLayer(pp_layers.py:257) — but the segmentation is
    "stack + shard leading axis" instead of per-rank layer assignment.

    Example (Llama middle):
        staged = PipelineStagedLayers([LlamaDecoderLayer(cfg) for _ in range(L)],
                                      lambda layer, params, h: ...,)
    """

    def __init__(self, layers: Sequence[Layer], num_micro: int = 1,
                 axis: str = "pp", remat: bool = True):
        super().__init__()
        # the template is used only to re-apply one layer functionally; keep
        # it OUT of the registries so its (stage-0) weights are not duplicated
        # as trainable params next to the stacked copies
        object.__setattr__(self, "template", layers[0])
        from ..nn.module import Parameter
        param_keys = set(layers[0].param_dict())
        stacked = stack_layer_params(layers)
        for k, v in stacked.items():
            name = "s__" + k.replace(".", "__")
            spec = (axis,) + (None,) * (v.ndim - 1)
            if k in param_keys:
                self.add_parameter(name, Parameter(v, spec=spec))
            else:
                # stage buffers (BN stats, rope caches) stay buffers
                self.register_buffer(name, v)
        self._stacked_keys = list(stacked.keys())
        self.num_micro = num_micro
        self.axis = axis
        self.remat = remat

    def _stacked(self):
        out = {}
        for k in self._stacked_keys:
            name = "s__" + k.replace(".", "__")
            out[k] = (self._parameters.get(name)
                      if name in self._parameters else self._buffers[name])
        return out

    def layer_apply(self, params_slice, h, *extra):
        out, _ = functional_call(self.template, params_slice, h, *extra,
                                 training=self.training)
        return out

    def forward(self, x, *extra):
        def apply_fn(sl, h):
            return self.layer_apply(sl, h, *extra)
        return pipeline_forward(self._stacked(), x, apply_fn,
                                axis=self.axis, num_micro=self.num_micro,
                                remat=self.remat)
