"""Pipeline parallelism (parity: fleet/meta_parallel/ — PipelineLayer
pp_layers.py:257, 1F1B scheduler pipeline_parallel.py:148/455, p2p handoff
p2p_communication.py:559; behavioral spec SURVEY §B.1).

TPU-native architecture: no per-rank interpreter or message bus. The whole
pipeline is ONE SPMD program under shard_map over the 'pp' mesh axis:

- homogeneous stage layers are STACKED — params get a leading layer axis
  sharded on pp (each device owns L/P layers, applied with lax.scan);
- the microbatch schedule is a lax.scan over T = M + P - 1 ticks; at tick t
  stage r computes microbatch t - r, then hands its activation to stage r+1
  with a single ring ppermute (the p2p send/recv pair);
- reverse pass: jax.grad differentiates through scan + ppermute, yielding
  the mirrored backward pipeline automatically (GPipe fill-drain schedule;
  activation memory bounded by remat of the stage body).

``pipeline_forward`` keeps the forward-only GPipe schedule (inference);
training uses ``pipeline_train_1f1b`` — a lockstep SPMD 1F1B schedule
(parity: pipeline_parallel.py:455, behavioral spec SURVEY §B.1) where each
tick runs one forward and one rematerialised backward per stage, so peak
activation memory is O(pp) stage inputs instead of O(num_micro), and
heterogeneous first/last stages (embedding source, loss sink) are expressed
as ``first_fn``/``last_fn`` with shared-parameter gradients merged by one
psum over the pp axis (parity: PipelineLayer shared embeddings,
pp_layers.py:257).
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..core.compat import shard_map
from ..core import mesh as mesh_lib
from ..nn.module import Layer, functional_call

__all__ = ["pipeline_forward", "stack_layer_params", "PipelineStagedLayers",
           "pipeline_train_1f1b"]


def stack_layer_params(layers: Sequence[Layer]) -> dict[str, jax.Array]:
    """Stack the path-keyed params of homogeneous layers along a new leading
    axis: list of L layers -> {path: [L, ...]} (the PipelineLayer
    LayerDesc-list collapses into one stacked tensor per weight)."""
    dicts = [l.state_dict(include_non_persistable_buffer=True) for l in layers]
    keys = dicts[0].keys()
    for d in dicts[1:]:
        if d.keys() != keys:
            raise ValueError("pipeline stages must be homogeneous")
    return {k: jnp.stack([d[k] for d in dicts]) for k in keys}


def pipeline_forward(stacked: dict[str, jax.Array], x: jax.Array,
                     layer_apply: Callable, *, mesh: Mesh | None = None,
                     axis: str = "pp", num_micro: int = 1,
                     remat: bool = True) -> jax.Array:
    """Run x through L stacked layers pipelined over the pp axis.

    stacked: {path: [L, ...]} (sharded or not — shard_map partitions by spec)
    x: [batch, ...] global batch; split into num_micro microbatches.
    layer_apply(params_slice, h) -> h : applies ONE layer.
    """
    mesh = mesh or mesh_lib.current_mesh()
    pp = mesh_lib.axis_size(axis, mesh) if mesh else 1
    if mesh is None or pp == 1:
        def body(h, sl):
            return layer_apply(sl, h), None
        out, _ = lax.scan(body, x, stacked)
        return out
    if x.shape[0] % num_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by {num_micro} microbatches")
    mb = x.shape[0] // num_micro
    xs = x.reshape(num_micro, mb, *x.shape[1:])

    apply_one = jax.checkpoint(layer_apply) if remat else layer_apply

    def stage_fn(local_params, h):
        # local_params leaves: [L/P, ...]; scan them over the microbatch act
        def body(carry, sl):
            return apply_one(sl, carry), None
        out, _ = lax.scan(body, h, local_params)
        return out

    T = num_micro + pp - 1
    perm_fwd = [(r, (r + 1) % pp) for r in range(pp)]

    def per_device(local_params, xs_local):
        r = lax.axis_index(axis)
        h0 = jnp.zeros((mb,) + xs_local.shape[2:], xs_local.dtype)
        outs0 = jnp.zeros_like(xs_local)

        def tick(carry, t):
            h_in, b_in, outs = carry
            m_idx = t - r  # microbatch this stage handles at tick t
            valid = (m_idx >= 0) & (m_idx < num_micro)
            # stage 0 reads from the input queue; others use the received act
            src = lax.cond(r == 0,
                           lambda _: lax.dynamic_index_in_dim(
                               xs_local, jnp.clip(m_idx, 0, num_micro - 1), 0,
                               keepdims=False),
                           lambda _: h_in, None)
            y = stage_fn(local_params, src)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            # last stage banks its finished microbatch locally
            outs = lax.cond(
                (r == pp - 1) & valid,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(m_idx, 0, num_micro - 1), 0),
                lambda o: o, outs)
            # streamed replication: finished microbatches ride a second ring
            # channel (last stage injects, everyone else forwards), so each
            # travels every link exactly once overlapped with compute —
            # half the ICI bytes of the old post-loop whole-buffer psum.
            # rank r holds the microbatch the last stage emitted r+1 hops
            # (= ticks) ago: m_b = (t - (r+1)) - (pp-1)
            m_b = t - r - pp
            outs = lax.cond(
                (r != pp - 1) & (m_b >= 0) & (m_b < num_micro),
                lambda o: lax.dynamic_update_index_in_dim(
                    o, b_in, jnp.clip(m_b, 0, num_micro - 1), 0),
                lambda o: o, outs)
            b_out = jnp.where(r == pp - 1, y, b_in)
            # hand off to the next stage (ring; stage P-1 -> 0 is ignored on
            # the h channel, it IS the injection point of the b channel)
            h_next, b_next = lax.ppermute((y, b_out), axis, perm_fwd)
            return (h_next, b_next, outs), None

        (_, b_last, outs), _ = lax.scan(tick, (h0, h0, outs0),
                                        jnp.arange(T))
        # drain: every microbatch was injected during the T main ticks —
        # the remaining hops only FORWARD the b ring (no stage compute)
        # until the furthest rank (pp-2) has banked the last microbatch

        def drain(carry, t):
            b_in, outs = carry
            m_b = t - r - pp
            outs = lax.cond(
                (r != pp - 1) & (m_b >= 0) & (m_b < num_micro),
                lambda o: lax.dynamic_update_index_in_dim(
                    o, b_in, jnp.clip(m_b, 0, num_micro - 1), 0),
                lambda o: o, outs)
            return (lax.ppermute(b_in, axis, perm_fwd), outs), None

        if pp > 1:
            (_, outs), _ = lax.scan(drain, (b_last, outs),
                                    jnp.arange(T, T + pp - 1))
        return outs

    pspec = jax.tree.map(lambda v: P(axis, *([None] * (v.ndim - 1))), stacked)
    # partial-manual shard_map (manual pp, auto dp/fsdp/mp) requires jit;
    # nested jit is inlined so this is free inside a compiled train step
    out = jax.jit(shard_map(per_device, mesh=mesh,
                            in_specs=(pspec, P()), out_specs=P(),
                            axis_names=frozenset({axis}),
                            check_vma=False))(stacked, xs)
    return out.reshape(x.shape[0], *out.shape[2:])


def pipeline_train_1f1b(stage_params, extra_params, micro_inputs,
                        first_fn: Callable, layer_apply: Callable,
                        last_fn: Callable, *, mesh: Mesh | None = None,
                        axis: str = "pp", remat: bool = True,
                        extra_manual_axes: Sequence[str] = (),
                        micro_in_specs=None, vpp: int = 1):
    """One pipelined forward+backward over microbatches with the 1F1B
    schedule (parity: PipelineParallel.forward_backward_pipeline,
    pipeline_parallel.py:455; spec SURVEY §B.1).

    The whole schedule is ONE SPMD program: shard_map manual over ``axis``
    (plus ``extra_manual_axes``, e.g. 'sep' for ring attention inside the
    stage body); every other mesh axis (dp/fsdp/mp) stays a GSPMD auto axis,
    so batch sharding and ZeRO/TP weight shardings compose untouched.

    Schedule: T = M + 2P - 2 lockstep ticks. At tick t stage r runs the
    forward of microbatch ``t - r`` and the backward of microbatch
    ``t - (2P - 2 - r)`` (the classic 1F1B interleaving: the last stage
    folds loss forward+backward into one tick, grads stream back one stage
    per tick). Backward rematerialises the stage from its saved *input*, so
    only O(P) stage inputs are alive — the reference's "one in-flight
    activation per stage depth" property — vs O(M) for fill-drain GPipe.

    Args:
      stage_params: pytree with leading stacked-layer dim on every leaf,
        sharded ``P(axis, ...)``.
      extra_params: pytree used by ``first_fn``/``last_fn`` (embedding, final
        norm, lm head). A param referenced by both (tied embeddings) gets its
        two gradient contributions summed by the final psum over ``axis`` —
        the reference's shared-embedding allreduce (pp_layers.py:257).
      micro_inputs: pytree, every leaf ``[M, ...]`` (microbatch-major).
      first_fn(extra, micro_in) -> h:        stage-0 source (embedding).
      layer_apply(param_slice, h) -> h:      one stacked layer.
      last_fn(extra, h, micro_in) -> (num, den): loss numerator/denominator
        (sum & token count); total loss = Σnum/Σden, gradients are of the
        total loss.
      vpp: virtual-pipeline chunks per device (parity: interleaved
        PipelineParallelWithInterleave, pipeline_parallel.py:942). With
        V = vpp > 1 each device owns V NON-adjacent stage chunks
        (stage s = c*P + r): forward of microbatch m = g*P + i runs at tick
        ``i + s + g*V*P`` and its backward at
        ``(S-1) + i + (S-1-s) + g*V*P`` — a closed-form interleaved
        timetable where every stage handoff is produced exactly one tick
        before its consumption on the adjacent device, so the same two ring
        ppermutes serve all chunks with NO in-transit buffering, and the
        warm-up/cool-down bubble shrinks from 2P to (1+1/V)P ticks.
        vpp=1 reduces to the plain 1F1B schedule.

    Returns (loss, d_stage_params, d_extra_params); d_stage stays sharded on
    ``axis`` like the params, d_extra is replicated over ``axis``.
    """
    mesh = mesh or mesh_lib.current_mesh()
    pp = mesh_lib.axis_size(axis, mesh) if mesh else 1
    V = int(vpp)
    apply_one = jax.checkpoint(layer_apply) if remat else layer_apply

    def stage_fn(local_params, h):
        def body(carry, sl):
            return apply_one(sl, carry), None
        out, _ = lax.scan(body, h, local_params)
        return out

    M = jax.tree.leaves(micro_inputs)[0].shape[0]

    if mesh is None or pp == 1:
        # degenerate: plain grad-accumulation over microbatches
        def total_loss(sp, ep):
            def mb(carry, mi):
                num, den = carry
                h = first_fn(ep, mi)
                h = stage_fn(sp, h)
                n, d = last_fn(ep, h, mi)
                return (num + n, den + d), None
            (num, den), _ = lax.scan(mb, (jnp.float32(0), jnp.float32(0)),
                                     micro_inputs)
            return num / den
        loss, grads = jax.value_and_grad(total_loss, argnums=(0, 1))(
            stage_params, extra_params)
        return loss, grads[0], grads[1]

    S = pp * V                       # virtual stages
    L_total = jax.tree.leaves(stage_params)[0].shape[0]
    if L_total % S:
        raise ValueError(f"stacked layer dim {L_total} must divide over "
                         f"{S} virtual stages (pp={pp} x vpp={V})")
    Lc = L_total // S                # layers per chunk
    if V > 1:
        # reorder stages so each device's V chunks are CONTIGUOUS under the
        # P(axis) leading-dim sharding: position (r, c, j) <- stage c*P+r
        import numpy as _np
        perm = _np.concatenate([
            _np.arange(Lc) + (c * pp + r) * Lc
            for r in range(pp) for c in range(V)])
        stage_params = jax.tree.map(lambda a: jnp.take(a, perm, axis=0),
                                    stage_params)
    # last tick = backward of stage 0 for the last microbatch:
    # b(0, M-1) = 2(S-1) + (M-1)%P + ((M-1)//P)*V*P  (partial groups still
    # advance a full V*P ticks, so ceil-group accounting, not M*V)
    T = 2 * (S - 1) + (M - 1) % pp + ((M - 1) // pp) * V * pp + 1
    B = 2 * pp + 1       # per-chunk input ring buffer; slot B-1 is trash
    perm_fwd = [(r, (r + 1) % pp) for r in range(pp)]
    perm_bwd = [(r, (r - 1) % pp) for r in range(pp)]
    manual = {axis, *extra_manual_axes}

    def per_device(sp_local, extra, micros):
        r = lax.axis_index(axis)
        m0 = jax.tree.map(lambda a: a[0], micros)
        h_struct = jax.eval_shape(first_fn, extra, m0)
        zero_h = jnp.zeros(h_struct.shape, h_struct.dtype)
        # local stacked params as [V, Lc, ...] chunk-major
        sp_ch = jax.tree.map(
            lambda a: a.reshape((V, Lc) + a.shape[1:]), sp_local)
        zeros_sp = jax.tree.map(jnp.zeros_like, sp_ch)
        zeros_ex = jax.tree.map(jnp.zeros_like, extra)

        def tick(carry, t):
            # NO lax.cond anywhere in this body: collectives (ring-attention
            # ppermutes in the stage, GSPMD-inserted psums for mp/dp/fsdp)
            # must be reached by EVERY device in lockstep — stage-dependent
            # work is expressed through masked VJP cotangents instead, so
            # masked contributions are exactly zero without divergent control
            # flow (the SPMD-safe formulation of the 1F1B/VPP schedule).
            h_in, g_in, buf, gsp, gex, num_acc, den_acc = carry

            # ---- decode the forward item: tick t = i + s + g*V*P with
            # s = c*P + r  =>  q = t - r = i + (c + g*V)*P
            qf = t - r
            i_f = jnp.mod(qf, pp)
            c_f = jnp.mod(qf // pp, V)
            g_f = qf // (V * pp)
            mf = g_f * pp + i_f
            valid_f = (qf >= 0) & (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)

            # ---- decode the backward item: t = 2(S-1) - c*P - r + i + g*V*P
            # =>  u = t + r - 2(S-1) + (V-1)*P = i + (V-1-c)*P + g*V*P
            u = t + r - 2 * (S - 1) + (V - 1) * pp
            i_b = jnp.mod(u, pp)
            cb = V - 1 - jnp.mod(u // pp, V)
            g_b = u // (V * pp)
            mb_ = g_b * pp + i_b
            valid_b = (u >= 0) & (mb_ >= 0) & (mb_ < M)
            mb_c = jnp.clip(mb_, 0, M - 1)
            cb_c = jnp.clip(cb, 0, V - 1)
            is_last_b = (r == pp - 1) & (cb_c == V - 1)

            mi_f = jax.tree.map(lambda a: lax.dynamic_index_in_dim(
                a, mf_c, 0, keepdims=False), micros)
            mi_b = jax.tree.map(lambda a: lax.dynamic_index_in_dim(
                a, mb_c, 0, keepdims=False), micros)

            # ---- forward: stage 0 (chunk 0 on device 0) sources from the
            # embedding, every other stage from the act received on the ring
            emb = first_fn(extra, mi_f)
            src = jnp.where((r == 0) & (c_f == 0), emb, h_in)
            slot_f = jnp.where(valid_f, mf_c % (B - 1), B - 1)
            buf = buf.at[c_f, slot_f].set(src)
            sp_f = jax.tree.map(lambda a: lax.dynamic_index_in_dim(
                a, c_f, 0, keepdims=False), sp_ch)
            y = stage_fn(sp_f, src)

            # ---- backward: ONE vjp serves both roles. The last stage
            # differentiates loss(stage(src_f)) seeded with cot_n=1; other
            # stages differentiate stage(saved input) seeded with the grad
            # received from downstream (cot_y). The unused cotangent is
            # zero, so the unused path contributes exactly 0 everywhere.
            slot_b = jnp.where(valid_b, mb_c % (B - 1), B - 1)
            src_saved = buf[cb_c, slot_b]
            src_bwd = jnp.where(is_last_b, src, src_saved)
            sp_b = jax.tree.map(lambda a: lax.dynamic_index_in_dim(
                a, cb_c, 0, keepdims=False), sp_ch)
            mi_bwd = jax.tree.map(
                lambda a, b_: jnp.where(is_last_b, a, b_), mi_f, mi_b)

            def composite(sp, s, ex):
                y2 = stage_fn(sp, s)
                n, d = last_fn(ex, y2, mi_bwd)
                return (y2, n), d

            (_, n), vjp_fn, d = jax.vjp(composite, sp_b, src_bwd, extra,
                                        has_aux=True)
            cot_n = jnp.where(is_last_b & valid_b, jnp.float32(1),
                              jnp.float32(0))
            cot_y = jnp.where((~is_last_b) & valid_b, g_in,
                              jnp.zeros_like(g_in))
            dsp, dsrc, dex = vjp_fn((cot_y, cot_n))

            # ---- stage-0 embedding backward (masked seed => exact zeros
            # elsewhere); shared (tied) params get both contributions summed
            seed = jnp.where((r == 0) & (cb_c == 0) & valid_b, dsrc,
                             jnp.zeros_like(dsrc))
            _, evjp = jax.vjp(lambda ex: first_fn(ex, mi_b), extra)
            (dex0,) = evjp(seed)

            # ---- accumulate (into the bwd item's chunk) + hand off
            gsp = jax.tree.map(
                lambda G, dd: G.at[cb_c].add(dd), gsp, dsp)
            gex = jax.tree.map(lambda a, x, yy: a + x + yy, gex, dex, dex0)
            num_acc = num_acc + jnp.where(is_last_b & valid_b, n, 0.0)
            den_acc = den_acc + jnp.where(is_last_b & valid_b, d, 0.0)
            y_send = jnp.where(valid_f, y, jnp.zeros_like(y))
            h_next = lax.ppermute(y_send, axis, perm_fwd)
            g_next = lax.ppermute(dsrc, axis, perm_bwd)
            return (h_next, g_next, buf, gsp, gex, num_acc, den_acc), None

        buf0 = jnp.zeros((V, B) + h_struct.shape, h_struct.dtype)
        carry0 = (zero_h, jnp.zeros_like(zero_h), buf0, zeros_sp, zeros_ex,
                  jnp.float32(0), jnp.float32(0))
        (_, _, _, gsp, gex, num, den), _ = lax.scan(tick, carry0,
                                                    jnp.arange(T))
        gsp = jax.tree.map(
            lambda G: G.reshape((V * Lc,) + G.shape[2:]), gsp)
        axes = tuple(manual)
        num = lax.psum(num, axes)
        den = lax.psum(den, axes)
        gex = jax.tree.map(lambda a: lax.psum(a, axes), gex)
        inv = jnp.where(den > 0, 1.0 / den, 0.0)
        # stage grads: psum over the extra manual axes only (they stay
        # sharded over `axis`); scale everything by 1/Σden so the gradients
        # are of the mean loss
        if extra_manual_axes:
            gsp = jax.tree.map(lambda a: lax.psum(a, tuple(extra_manual_axes)),
                               gsp)
        gsp = jax.tree.map(lambda a: (a * inv).astype(a.dtype), gsp)
        gex = jax.tree.map(lambda a: (a * inv).astype(a.dtype), gex)
        return num * inv, gsp, gex

    sp_spec = jax.tree.map(lambda v: P(axis, *([None] * (v.ndim - 1))),
                           stage_params)
    if micro_in_specs is None:
        micro_in_specs = jax.tree.map(lambda v: P(), micro_inputs)
    ex_spec = jax.tree.map(lambda v: P(), extra_params)
    out_specs = (P(), sp_spec, ex_spec)
    # partial-manual shard_map (manual pp/sep, auto dp/fsdp/mp) requires jit;
    # nested jit is inlined so this is free inside a compiled train step
    fn = jax.jit(shard_map(per_device, mesh=mesh,
                           in_specs=(sp_spec, ex_spec, micro_in_specs),
                           out_specs=out_specs, axis_names=frozenset(manual),
                           check_vma=False))
    loss, d_stage, d_extra = fn(stage_params, extra_params, micro_inputs)
    if V > 1:
        # undo the chunk-contiguous reorder so grads match the caller's
        # original layer order
        import numpy as _np
        inv = _np.argsort(perm)
        d_stage = jax.tree.map(lambda a: jnp.take(a, inv, axis=0), d_stage)
    return loss, d_stage, d_extra


class PipelineStagedLayers(Layer):
    """Module owning stacked homogeneous layers, executed pipelined.

    Parity: PipelineLayer(pp_layers.py:257) — but the segmentation is
    "stack + shard leading axis" instead of per-rank layer assignment.

    Example (Llama middle):
        staged = PipelineStagedLayers([LlamaDecoderLayer(cfg) for _ in range(L)],
                                      lambda layer, params, h: ...,)
    """

    def __init__(self, layers: Sequence[Layer], num_micro: int = 1,
                 axis: str = "pp", remat: bool = True):
        super().__init__()
        # the template is used only to re-apply one layer functionally; keep
        # it OUT of the registries so its (stage-0) weights are not duplicated
        # as trainable params next to the stacked copies
        object.__setattr__(self, "template", layers[0])
        from ..nn.module import Parameter
        param_keys = set(layers[0].param_dict())
        stacked = stack_layer_params(layers)
        for k, v in stacked.items():
            name = "s__" + k.replace(".", "__")
            spec = (axis,) + (None,) * (v.ndim - 1)
            if k in param_keys:
                self.add_parameter(name, Parameter(v, spec=spec))
            else:
                # stage buffers (BN stats, rope caches) stay buffers
                self.register_buffer(name, v)
        self._stacked_keys = list(stacked.keys())
        self.num_micro = num_micro
        self.axis = axis
        self.remat = remat

    def _stacked(self):
        out = {}
        for k in self._stacked_keys:
            name = "s__" + k.replace(".", "__")
            out[k] = (self._parameters.get(name)
                      if name in self._parameters else self._buffers[name])
        return out

    def layer_apply(self, params_slice, h, *extra):
        out, _ = functional_call(self.template, params_slice, h, *extra,
                                 training=self.training)
        return out

    def forward(self, x, *extra):
        def apply_fn(sl, h):
            return self.layer_apply(sl, h, *extra)
        return pipeline_forward(self._stacked(), x, apply_fn,
                                axis=self.axis, num_micro=self.num_micro,
                                remat=self.remat)
