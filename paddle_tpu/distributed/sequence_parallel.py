"""Sequence/context parallelism (parity: SURVEY §5.7's three mechanisms).

1. **Megatron-SP** (fleet/utils/sequence_parallel_utils.py): activations
   sequence-sharded outside attention. TPU-native: sharding constraints on
   the seq axis; Column/RowSequenceParallelLinear are annotation shims whose
   allgather/reduce-scatter GSPMD inserts.
2. **SEP / Ulysses** (meta_parallel/segment_parallel.py:26): all-to-all
   reshard between seq-sharded and head-sharded layouts around attention —
   here an explicit ``lax.all_to_all`` inside shard_map over the 'sep' axis.
3. **Ring attention** (capability the reference lacks — included for
   long-context parity): sequence-sharded flash attention with K/V blocks
   rotating over ``ppermute``, partial results merged in log-sum-exp space
   using the Pallas kernel's stored LSE. Fully differentiable (scan +
   ppermute + custom-vjp flash).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..core.compat import shard_map
from ..core import mesh as mesh_lib
from ..nn.module import Layer
from ..ops.pallas.flash_attention import flash_attention_with_lse
from .fleet.mp_layers import ColumnParallelLinear, RowParallelLinear, mark_sharding

__all__ = ["ulysses_attention", "ring_attention", "scatter_to_sequence_parallel",
           "gather_from_sequence_parallel", "ColumnSequenceParallelLinear",
           "RowSequenceParallelLinear", "sep_reshard_qkv", "sep_reshard_out",
           "manual_sep_region", "current_manual_sep", "ring_attention_manual"]

# Trace-time flag: set while tracing code that is INSIDE a shard_map manual
# over the sep axis (e.g. the 1F1B pipeline body), so seq-sharded-aware
# layers (LlamaAttention) switch to ring attention + offset rope positions.
_MANUAL_SEP: list[str | None] = [None]


import contextlib


@contextlib.contextmanager
def manual_sep_region(axis: str | None):
    """Mark the enclosed trace as running inside a manual-sep shard_map."""
    prev = _MANUAL_SEP[0]
    _MANUAL_SEP[0] = axis
    try:
        yield
    finally:
        _MANUAL_SEP[0] = prev


def current_manual_sep() -> str | None:
    return _MANUAL_SEP[0]


# ---------- Megatron-SP annotation shims ----------

def scatter_to_sequence_parallel(x, axis="sep"):
    """Parity: sequence_parallel_utils.ScatterOp — constrain seq dim sharded."""
    return mark_sharding(x, None, axis, *([None] * (x.ndim - 2)))


def gather_from_sequence_parallel(x, axis="sep"):
    """Parity: GatherOp — constrain seq dim replicated (allgather)."""
    return mark_sharding(x, *([None] * x.ndim))


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Parity: sequence_parallel_utils.py:395 — allgather(seq) then column
    matmul; GSPMD derives it from input seq-sharded + output head-sharded."""

    def forward(self, x):
        x = gather_from_sequence_parallel(x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row matmul then reduce-scatter onto the seq axis."""

    def forward(self, x):
        y = super().forward(x)
        return scatter_to_sequence_parallel(y)


# ---------- Ulysses (SEP all-to-all) ----------

def sep_reshard_qkv(t, axis_name="sep"):
    """Inside shard_map: [b, s/P, h, d] -> [b, s, h/P, d] via all-to-all
    (parity: the reshard around attention in segment_parallel / Ulysses)."""
    return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)


def sep_reshard_out(t, axis_name="sep"):
    """Inverse: [b, s, h/P, d] -> [b, s/P, h, d]."""
    return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh | None = None, axis: str = "sep",
                      causal: bool = True, attention_fn=None):
    """Ulysses sequence parallelism: inputs seq-sharded [b, S, h, d] (global
    view), attention computed head-sharded after all-to-all. Requires
    num_heads % sep_degree == 0."""
    from ..nn.functional.attention import _xla_attention
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or mesh_lib.axis_size(axis, mesh) == 1:
        fn = attention_fn or (lambda q, k, v: _xla_attention(q, k, v, is_causal=causal))
        return fn(q, k, v)
    inner_attn = attention_fn or (lambda q, k, v: _xla_attention(q, k, v,
                                                                 is_causal=causal))

    def local_fn(q, k, v):
        qh = sep_reshard_qkv(q, axis)
        kh = sep_reshard_qkv(k, axis)
        vh = sep_reshard_qkv(v, axis)
        oh = inner_attn(qh, kh, vh)
        return sep_reshard_out(oh, axis)

    spec = P(None, axis, None, None)
    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


# ---------- Ring attention ----------

def _merge_lse(o1, lse1, o2, lse2):
    """Combine two attention partials in log-sum-exp space.
    o: [b, sq, h, d]; lse: [b, h, sq]."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    lse = m + jnp.log(w1 + w2)
    w1n = (w1 / (w1 + w2)).transpose(0, 2, 1)[..., None]  # [b, sq, h, 1]
    w2n = (w2 / (w1 + w2)).transpose(0, 2, 1)[..., None]
    return o1 * w1n + o2 * w2n, lse


def _ring_rotate(t, axis, nsteps):
    # send to the next rank: rank r's block moves to r+1, so after i steps
    # rank r holds the block owned by (r - i) mod P
    perm = [(r, (r + 1) % nsteps) for r in range(nsteps)]
    return lax.ppermute(t, axis, perm)


def _rep_kv(t, rep):
    """GQA: expand kvh key/value heads to the query head count. Done
    per-ring-step so the rotating buffers (and their backward accumulators)
    stay at kvh heads — h/kvh less ICI traffic than pre-repeating."""
    return t if rep == 1 else jnp.repeat(t, rep, axis=2)


def _reduce_kv_heads(g, rep):
    """Fold gradient heads back onto the kvh grouped heads."""
    if rep == 1:
        return g
    b, s, h, d = g.shape
    return g.reshape(b, s, h // rep, rep, d).sum(3)


def _ring_fwd_loop(q, k, v, axis, nsteps, causal, scale):
    my = lax.axis_index(axis)
    NEG = jnp.float32(-1e30)
    b, sl, h, d = q.shape
    rep = h // k.shape[2]

    def step(carry, i):
        o, lse, kb, vb = carry
        src = jnp.mod(my - i, nsteps)  # owner of the block we currently hold

        def do_skip(_):
            return (jnp.zeros_like(q, jnp.float32),
                    jnp.full((b, h, sl), NEG, jnp.float32))

        def do_full(_):
            ob, lseb = flash_attention_with_lse(q, _rep_kv(kb, rep),
                                                _rep_kv(vb, rep),
                                                causal=False, scale=scale)
            return ob.astype(jnp.float32), lseb

        def do_causal(_):
            ob, lseb = flash_attention_with_lse(q, _rep_kv(kb, rep),
                                                _rep_kv(vb, rep),
                                                causal=True, scale=scale)
            return ob.astype(jnp.float32), lseb

        if causal:
            case = jnp.where(src == my, 2, jnp.where(src < my, 1, 0))
            ob, lseb = lax.switch(case, [do_skip, do_full, do_causal], None)
        else:
            ob, lseb = do_full(None)
        o, lse = _merge_lse(o, lse, ob, lseb)
        return (o, lse, _ring_rotate(kb, axis, nsteps),
                _ring_rotate(vb, axis, nsteps)), None

    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((b, h, sl), NEG, jnp.float32)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(nsteps))
    return o.astype(q.dtype), lse


def _ring_core_impl(q, k, v, axis, nsteps, causal, scale):
    out, _ = _ring_fwd_loop(q, k, v, axis, nsteps, causal, scale)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_core(q, k, v, axis, nsteps, causal, scale):
    return _ring_core_impl(q, k, v, axis, nsteps, causal, scale)


def _ring_core_fwd(q, k, v, axis, nsteps, causal, scale):
    out, lse = _ring_fwd_loop(q, k, v, axis, nsteps, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_core_bwd(axis, nsteps, causal, scale, res, do):
    """Ring backward: dk/dv accumulators travel WITH their k/v block around
    the ring, arriving home after a full revolution; dq accumulates locally.
    Uses the global LSE + delta trick (delta computed once from the merged
    output is valid for every block's partial gradient)."""
    from ..ops.pallas.flash_attention import flash_block_grads
    q, k, v, out, lse = res
    my = lax.axis_index(axis)
    rep = q.shape[2] // k.shape[2]
    delta = jnp.moveaxis(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1), 2, 1)

    def step(carry, i):
        dq, kb, vb, dkb, dvb = carry
        src = jnp.mod(my - i, nsteps)

        def do_skip(_):
            return (jnp.zeros_like(q, jnp.float32),
                    jnp.zeros_like(kb, jnp.float32),
                    jnp.zeros_like(vb, jnp.float32))

        def grads(causal_flag):
            def f(_):
                a, b_, c = flash_block_grads(q, _rep_kv(kb, rep),
                                             _rep_kv(vb, rep), do, lse, delta,
                                             scale=scale, causal=causal_flag)
                return (a.astype(jnp.float32),
                        _reduce_kv_heads(b_.astype(jnp.float32), rep),
                        _reduce_kv_heads(c.astype(jnp.float32), rep))
            return f

        if causal:
            case = jnp.where(src == my, 2, jnp.where(src < my, 1, 0))
            dqp, dkp, dvp = lax.switch(case, [do_skip, grads(False), grads(True)],
                                       None)
        else:
            dqp, dkp, dvp = grads(False)(None)
        dq = dq + dqp
        dkb = dkb + dkp
        dvb = dvb + dvp
        return (dq, _ring_rotate(kb, axis, nsteps), _ring_rotate(vb, axis, nsteps),
                _ring_rotate(dkb, axis, nsteps), _ring_rotate(dvb, axis, nsteps)), None

    init = (jnp.zeros_like(q, jnp.float32), k, v,
            jnp.zeros_like(k, jnp.float32), jnp.zeros_like(v, jnp.float32))
    (dq, _, _, dk, dv), _ = lax.scan(step, init, jnp.arange(nsteps))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention_manual(q, k, v, axis: str = "sep", causal: bool = True,
                          scale: float | None = None):
    """Ring attention for callers ALREADY inside a shard_map manual over
    ``axis`` (e.g. the 1F1B pipeline body): q/k/v are local seq shards
    [b, S/P, h, d]; GQA (fewer k/v heads) is supported — k/v blocks rotate
    at kv-head width. Public entry point for model code."""
    import math
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    nsteps = mesh_lib.axis_size(axis)
    return _ring_core(q, k, v, axis, nsteps, causal, scale)


def ring_attention(q, k, v, mesh: Mesh | None = None, axis: str = "sep",
                   causal: bool = True, scale: float | None = None):
    """Ring (blockwise) attention over the 'sep' mesh axis: memory O(S/P)
    per device, K/V streamed over ICI. Inputs [b, S, h, d] seq-sharded."""
    import math
    mesh = mesh or mesh_lib.current_mesh()
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    nsteps = mesh_lib.axis_size(axis, mesh) if mesh else 1
    if mesh is None or nsteps == 1:
        from ..ops.pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    spec = P(None, axis, None, None)

    def fn(q, k, v):
        return _ring_core(q, k, v, axis, nsteps, causal, scale)

    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                     check_vma=False)(q, k, v)
