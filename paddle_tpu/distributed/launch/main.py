"""Launcher implementation (parity: distributed/launch/main.py:20 launch(),
plus the elastic gang-restart loop of ElasticManager,
fleet/elastic/manager.py:124 — a worker death triggers a collective
relaunch of the whole gang up to --max_restarts times, with the restart
epoch exported so workers can resume from their latest checkpoint)."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

from ..fleet.preempt import EXIT_PREEMPTED
from ..watchdog import EXIT_WATCHDOG_ABORT

__all__ = ["launch", "main", "classify_exit"]


def classify_exit(rc: int) -> str:
    """Exit-code contract (RESILIENCE.md): map a worker's return code to a
    failure class the restart policy and the logs can reason about."""
    if rc == 0:
        return "clean"
    if rc == EXIT_WATCHDOG_ABORT:
        return "watchdog-abort"
    if rc == EXIT_PREEMPTED:
        return "preempted"
    if rc < 0:
        try:
            return f"killed-by-{signal.Signals(-rc).name}"
        except ValueError:
            return f"killed-by-signal-{-rc}"
    return "crash"


def _spawn_gang(args, n, restart_epoch, log_files):
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update({
            "COORDINATOR_ADDRESS": args.master,
            "NUM_PROCESSES": str(n),
            "PROCESS_ID": str(rank),
            # reference-compatible names
            "PADDLE_TRAINERS_NUM": str(n),
            "PADDLE_TRAINER_ID": str(rank),
            # elastic: restart counter (PADDLE_ELASTIC-style signal for the
            # training script to resume from its latest checkpoint)
            "PADDLE_RESTART_EPOCH": str(restart_epoch),
        })
        if args.devices:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count={args.devices}").strip()
        stdout = None
        if args.log_dir:
            f = open(os.path.join(
                args.log_dir, f"worker.{rank}.r{restart_epoch}.log"), "w")
            log_files.append(f)
            stdout = f
        elif rank != 0:
            stdout = subprocess.DEVNULL
        procs.append(subprocess.Popen(
            [sys.executable, args.script, *args.script_args], env=env,
            stdout=stdout, stderr=subprocess.STDOUT if stdout else None))
    return procs


def launch(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc_per_node", "--nprocs", type=int, default=1)
    parser.add_argument("--master", default="127.0.0.1:12355",
                        help="coordinator address (host:port)")
    parser.add_argument("--log_dir", default=None)
    parser.add_argument("--devices", default=None,
                        help="devices per process (cpu simulation: count)")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="elastic: gang-restart the job up to this many "
                             "times when a worker dies (0 = fail fast)")
    parser.add_argument("--restart_backoff", type=float, default=0.5,
                        help="elastic: base seconds slept before a gang "
                             "restart; doubles each restart (capped at "
                             "30s) so a crash-looping job does not spin")
    parser.add_argument("--grace_period", type=float, default=10.0,
                        help="seconds workers get between SIGTERM (forwarded "
                             "on launcher shutdown/preemption) and SIGKILL — "
                             "the window for draining async saves and taking "
                             "a final checkpoint")
    parser.add_argument("--auto_tuner_json", default=None,
                        help="parity: launch --auto_tuner_json — a JSON "
                             "model spec; the planner picks dp/fsdp/mp/pp "
                             "degrees and exports them as PADDLE_AUTO_* env")
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    n = args.nproc_per_node
    if args.auto_tuner_json:
        # launch-time distributed-config search (parity:
        # distributed/auto_tuner/tuner.py:21 driven from launch)
        import json as _json
        from ..auto_tuner import AutoTuner, HardwareSpec, ModelSpec
        with open(args.auto_tuner_json) as f:
            spec = _json.load(f)
        hw = HardwareSpec(n_devices=int(spec.pop("n_devices", n)),
                          **{k: spec.pop(k) for k in
                             ("hbm_bytes", "flops", "ici_bw", "dcn_bw")
                             if k in spec})
        best = AutoTuner(ModelSpec(**spec), hw).tune()[0]
        print(f"[auto_tuner] selected {best.degrees} "
              f"(modeled step {best.step_time:.3f}s, "
              f"mem {best.mem_bytes / 1e9:.1f} GB)", file=sys.stderr)
        for k, v in best.degrees.items():
            os.environ[f"PADDLE_AUTO_{k.upper()}_DEGREE"] = str(v)
        os.environ["PADDLE_AUTO_MICRO_BATCH"] = str(best.micro_batch)
    log_files: list = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    restart_epoch = 0
    procs = _spawn_gang(args, n, restart_epoch, log_files)

    def _kill_all(*_):
        # forward SIGTERM (the preemption shape workers' PreemptionGuard
        # listens for), give them the grace window, then SIGKILL stragglers
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + args.grace_period
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    shutting_down = [False]

    def _on_sigterm(*_):
        # graceful shutdown (preemption): forward to workers so they can
        # drain saves + final-checkpoint; do NOT treat the resulting worker
        # exits as failures needing an elastic restart
        shutting_down[0] = True
        _kill_all()

    signal.signal(signal.SIGTERM, _on_sigterm)
    code = 0
    try:
        while procs:
            failed = False
            for p in list(procs):
                rc = p.poll()
                if rc is not None:
                    procs.remove(p)
                    if rc != 0:
                        failed = True
                        if code == 0:  # keep the first real failure code,
                            code = rc  # not the SIGTERM of siblings we kill
            if failed and not shutting_down[0]:
                _kill_all()
                procs.clear()
                if restart_epoch < args.max_restarts:
                    restart_epoch += 1
                    # exponential backoff: an immediately-fatal config would
                    # otherwise burn every restart within a second
                    delay = min(args.restart_backoff
                                * (2 ** (restart_epoch - 1)), 30.0)
                    print(f"[elastic] worker failure (rc={code}, "
                          f"{classify_exit(code)}); gang restart "
                          f"{restart_epoch}/{args.max_restarts} "
                          f"in {delay:.1f}s", file=sys.stderr)
                    time.sleep(delay)
                    code = 0
                    procs = _spawn_gang(args, n, restart_epoch, log_files)
            time.sleep(0.2)
    finally:
        _kill_all()
        for f in log_files:
            f.close()
    return code


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
