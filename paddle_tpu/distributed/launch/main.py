"""Launcher implementation (parity: distributed/launch/main.py:20 launch())."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def launch(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc_per_node", "--nprocs", type=int, default=1)
    parser.add_argument("--master", default="127.0.0.1:12355",
                        help="coordinator address (host:port)")
    parser.add_argument("--log_dir", default=None)
    parser.add_argument("--devices", default=None,
                        help="devices per process (cpu simulation: count)")
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    n = args.nproc_per_node
    procs: list[subprocess.Popen] = []
    log_files = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for rank in range(n):
        env = dict(os.environ)
        env.update({
            "COORDINATOR_ADDRESS": args.master,
            "NUM_PROCESSES": str(n),
            "PROCESS_ID": str(rank),
            # reference-compatible names
            "PADDLE_TRAINERS_NUM": str(n),
            "PADDLE_TRAINER_ID": str(rank),
        })
        if args.devices:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count={args.devices}").strip()
        stdout = None
        if args.log_dir:
            f = open(os.path.join(args.log_dir, f"worker.{rank}.log"), "w")
            log_files.append(f)
            stdout = f
        elif rank != 0:
            stdout = subprocess.DEVNULL
        procs.append(subprocess.Popen(
            [sys.executable, args.script, *args.script_args], env=env,
            stdout=stdout, stderr=subprocess.STDOUT if stdout else None))

    def _kill_all(*_):
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGTERM, _kill_all)
    code = 0
    try:
        while procs:
            for p in list(procs):
                rc = p.poll()
                if rc is not None:
                    procs.remove(p)
                    if rc != 0:
                        if code == 0:  # keep the first real failure code,
                            code = rc  # not the SIGTERM of siblings we kill
                        _kill_all()
            time.sleep(0.2)
    finally:
        _kill_all()
        for f in log_files:
            f.close()
    return code


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
