"""Distributed launcher (parity: python/paddle/distributed/launch —
``python -m paddle_tpu.distributed.launch --nproc_per_node=N train.py``).

On TPU pods the runtime launches one process per host (GKE/TPU-VM); this
launcher covers the single-host multi-process case (CPU simulation and
jax.distributed testing) the reference covers with its collective controller:
it spawns N local processes with COORDINATOR_ADDRESS/PROCESS_ID env and
aggregates logs — the TCPStore rendezvous is jax's coordinator service.
"""

from .main import launch  # noqa: F401
