"""Minimal RPC (parity: python/paddle/distributed/rpc + the brpc-based
fluid/distributed/rpc agent — init_rpc, rpc_sync, rpc_async, shutdown).

TPU-native scope: control-plane RPC between host processes (data-plane
communication is XLA collectives). Implementation is a small TCP +
pickle request/response server per worker — the structural equivalent of
the reference's brpc agent, standard library only.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
           "get_all_worker_infos", "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


_STATE: dict = {"server": None, "workers": {}, "me": None, "pool": None}


def _send_msg(sock, obj):
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    (n,) = struct.unpack("!I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return pickle.loads(buf)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            fn, args, kwargs = _recv_msg(self.request)
            try:
                result = fn(*args, **kwargs)
                _send_msg(self.request, ("ok", result))
            except BaseException as e:  # noqa: BLE001 — ship to caller
                _send_msg(self.request, ("err", e))
        except ConnectionError:
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name: str, rank: int | None = None, world_size: int | None = None,
             master_endpoint: str | None = None, workers: list | None = None):
    """Start this process's RPC server and learn the peer table.

    Simplified rendezvous: pass ``workers`` as a list of "name:ip:port"
    strings (every process passes the same list), or rely on
    PADDLE_TRAINER_ID + a master_endpoint-derived port block.
    """
    if workers is not None:
        table = {}
        for i, spec in enumerate(workers):
            wname, ip, port = spec.split(":")
            table[wname] = WorkerInfo(wname, i, ip, int(port))
        me = table[name]
    else:
        import os
        rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        world_size = world_size or int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        host, base = (master_endpoint or "127.0.0.1:18765").split(":")
        table = {f"worker{i}": WorkerInfo(f"worker{i}", i, host,
                                          int(base) + i)
                 for i in range(world_size)}
        me = table.get(name) or WorkerInfo(name, rank, host,
                                           int(base) + rank)
        table[name] = me
    server = _Server((me.ip, me.port), _Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    _STATE.update(server=server, workers=table, me=me,
                  pool=ThreadPoolExecutor(max_workers=8))
    return me


def _call(to: str, fn, args, kwargs, timeout):
    info = _STATE["workers"][to]
    with socket.create_connection((info.ip, info.port), timeout=timeout) as s:
        _send_msg(s, (fn, args or (), kwargs or {}))
        s.settimeout(timeout)
        status, payload = _recv_msg(s)
    if status == "err":
        raise payload
    return payload


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout: float = 60.0):
    """Call ``fn(*args, **kwargs)`` on worker ``to``; blocks for the result
    (parity: paddle.distributed.rpc.rpc_sync)."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout: float = 60.0) -> Future:
    """Async variant returning a Future with .result()/.wait()."""
    fut = _STATE["pool"].submit(_call, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle API alias
    return fut


def get_worker_info(name: str | None = None) -> WorkerInfo:
    return _STATE["workers"][name] if name else _STATE["me"]


def get_all_worker_infos():
    return list(_STATE["workers"].values())


def shutdown():
    if _STATE["server"] is not None:
        _STATE["server"].shutdown()
        _STATE["server"].server_close()
        _STATE["server"] = None
    if _STATE["pool"] is not None:
        _STATE["pool"].shutdown(wait=False)
        _STATE["pool"] = None
