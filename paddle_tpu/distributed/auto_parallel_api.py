"""Semi-auto parallel API (parity: python/paddle/distributed/auto_parallel/api.py
— shard_tensor:129, reshard:347, shard_layer:446, dtensor_from_fn).

The reference's DistTensor(local tensor + TensorDistAttr{mesh, dims_mapping,
partial}) IS jax.Array + NamedSharding: placements [Shard(i)/Replicate/Partial]
map to PartitionSpec entries, InferSpmd+reshard-per-op collapses into GSPMD
propagation, and explicit ``reshard`` is a device_put / with_sharding_constraint.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core import mesh as mesh_lib
from ..nn.module import Layer

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "reshard", "shard_layer", "dtensor_from_fn", "shard_dataloader",
           "unshard_dtensor", "placements_to_spec"]


class ProcessMesh:
    """Parity: paddle.distributed.ProcessMesh — thin wrapper building a
    jax Mesh from an ndarray of ranks + dim names."""

    def __init__(self, mesh: Sequence, dim_names: Sequence[str] | None = None):
        import numpy as np
        arr = np.asarray(mesh)
        self.shape = arr.shape
        self.dim_names = tuple(dim_names) if dim_names else tuple(
            f"d{i}" for i in range(arr.ndim))
        devs = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self.jax_mesh = Mesh(devs, self.dim_names)

    def __enter__(self):
        self._ctx = mesh_lib.use_mesh(self.jax_mesh)
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


class Shard:
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard({self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class Partial:
    """Pending-reduction placement. jax has no user-visible partial arrays;
    a Partial placement is resolved to Replicate via psum at reshard points
    (matching the reference's p->r reshard function)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type


def placements_to_spec(placements, mesh_names, ndim) -> PartitionSpec:
    """[Shard(0), Replicate] over mesh axes -> PartitionSpec rows."""
    entries: list = [None] * ndim
    for axis_name, p in zip(mesh_names, placements):
        if isinstance(p, Shard):
            if entries[p.dim] is None:
                entries[p.dim] = axis_name
            elif isinstance(entries[p.dim], tuple):
                entries[p.dim] = entries[p.dim] + (axis_name,)
            else:
                entries[p.dim] = (entries[p.dim], axis_name)
    return PartitionSpec(*entries)


def _resolve_mesh(mesh):
    if isinstance(mesh, ProcessMesh):
        return mesh.jax_mesh
    if isinstance(mesh, Mesh):
        return mesh
    m = mesh_lib.current_mesh()
    if m is None:
        raise ValueError("no mesh: pass mesh= or enter use_mesh(...)")
    return m


def shard_tensor(data, mesh=None, placements=None, dtype=None, stop_gradient=True):
    """Place a tensor on the mesh with given placements (parity: api.py:129)."""
    from ..ops.creation import to_tensor
    m = _resolve_mesh(mesh)
    x = to_tensor(data, dtype=dtype)
    placements = placements or [Replicate() for _ in m.axis_names]
    spec = placements_to_spec(placements, m.axis_names, x.ndim)
    return jax.device_put(x, NamedSharding(m, spec))


def reshard(x, mesh=None, placements=None, spec: PartitionSpec | None = None):
    """Change an array's distribution (parity: api.py:347; engine:
    phi reshard functions SURVEY §B.3 — here XLA emits the collective)."""
    m = _resolve_mesh(mesh)
    if spec is None:
        spec = placements_to_spec(placements or [], m.axis_names, x.ndim)
    target = NamedSharding(m, spec)
    if isinstance(jax.core.get_aval(x), jax.core.ShapedArray) and not isinstance(
            x, jax.Array):
        # inside a trace: constraint, XLA inserts the reshard collective
        return jax.lax.with_sharding_constraint(x, target)
    return jax.device_put(x, target)


def unshard_dtensor(x):
    """Gather to a fully replicated array (parity: dtensor_to_local)."""
    m = mesh_lib.current_mesh()
    if m is None:
        return x
    return jax.device_put(x, NamedSharding(m, PartitionSpec()))


def shard_layer(layer: Layer, process_mesh=None, shard_fn: Callable | None = None,
                input_fn=None, output_fn=None) -> Layer:
    """Shard a layer's params in place (parity: api.py:446).

    ``shard_fn(name, sublayer)`` may call ``sublayer.set_param_spec``; default
    uses specs already attached at Parameter creation (Linear weight_spec etc.).
    """
    m = _resolve_mesh(process_mesh)
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub)
    specs = layer.spec_dict()
    params = layer.param_dict()
    new = {}
    for k, v in params.items():
        spec = specs.get(k)
        pspec = PartitionSpec(*spec) if spec else PartitionSpec()
        new[k] = jax.device_put(v, NamedSharding(m, pspec))
    layer.set_state_dict(new)
    return layer


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    out = fn(*args, **kwargs)
    return shard_tensor(out, mesh, placements)


def shard_dataloader(dataloader, meshes=None, shard_dims="dp", input_keys=None):
    """Wrap a DataLoader so yielded host batches are placed dp-sharded on the
    mesh (parity: auto_parallel ShardDataloader)."""
    m = _resolve_mesh(meshes)

    class _Sharded:
        def __iter__(self):
            for batch in dataloader:
                def place(a):
                    spec = PartitionSpec(shard_dims, *([None] * (a.ndim - 1)))
                    return jax.device_put(a, NamedSharding(m, spec))
                yield jax.tree.map(place, batch)

        def __len__(self):
            return len(dataloader)

    return _Sharded()
