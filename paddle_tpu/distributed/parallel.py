"""Environment bootstrap + DataParallel wrapper
(parity: python/paddle/distributed/parallel.py — init_parallel_env:943,
DataParallel:202).

On TPU, process bootstrap is ``jax.distributed.initialize`` (the TCPStore/
NCCL-unique-id rendezvous collapses into the JAX coordinator), and DP is a
sharding, not a wrapper with gradient hooks: the EagerReducer's fused
allreduce (reducer.cc, SURVEY §B.4) is what XLA emits automatically when the
batch axis is sharded and grads are computed under jit. DataParallel here
therefore only (a) records the mesh axis, (b) provides no_sync semantics via
gradient accumulation, preserving the reference API.
"""

from __future__ import annotations

import contextlib
import os

import jax

from ..core import mesh as mesh_lib
from ..nn.module import Layer

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "DataParallel",
           "ParallelEnv"]

_initialized = [False]


def init_parallel_env(coordinator_address: str | None = None,
                      num_processes: int | None = None,
                      process_id: int | None = None):
    """Multi-host bootstrap. Single-process (one host driving its chips) needs
    no init — jax sees all local devices; multi-host reads the standard env
    (COORDINATOR_ADDRESS / PADDLE_TRAINER_* compatible)."""
    if _initialized[0]:
        return
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None:
        n = os.environ.get("PADDLE_TRAINERS_NUM") or os.environ.get("NUM_PROCESSES")
        num_processes = int(n) if n else None
    if process_id is None:
        r = os.environ.get("PADDLE_TRAINER_ID") or os.environ.get("PROCESS_ID")
        process_id = int(r) if r else None
    if coordinator_address and num_processes and num_processes > 1:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    _initialized[0] = True


def get_rank(group=None) -> int:
    return jax.process_index()


def get_world_size(group=None) -> int:
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0


class DataParallel(Layer):
    """Wraps a layer for data-parallel training (parity: paddle.DataParallel).

    Under GSPMD the wrapped forward is unchanged; gradient averaging across
    the 'dp' mesh axis happens inside jit when the loss is a mean over a
    dp-sharded batch. ``no_sync`` is provided for grad-accumulation parity:
    it simply marks that the caller accumulates grads host-side.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, mesh=None, axis="dp"):
        super().__init__()
        self._layers = layers
        self.axis = axis
        self.mesh = mesh or mesh_lib.current_mesh()
        self.find_unused_parameters = find_unused_parameters
        self._in_no_sync = False

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        self._in_no_sync = True
        try:
            yield
        finally:
            self._in_no_sync = False

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def scale_loss(self, loss):
        return loss
