"""Parameter/grad/optimizer-state sharding — ZeRO stages
(parity: python/paddle/distributed/sharding/group_sharded.py:40
group_sharded_parallel + fleet GroupShardedStage2/3, DygraphShardingOptimizer;
behavioral spec SURVEY §B.2).

TPU-native: all three stages are expressions of ONE mechanism — shard the
param (and thus its grad and optimizer state, which inherit the sharding) on
the 'fsdp' mesh axis and let GSPMD insert allgather-on-use /
reduce-scatter-on-grad:

- stage 1 (os):      shard only optimizer state → params replicated, opt
                     state placed with a sharded spec at init.
- stage 2 (os_g):    + grads reduce-scattered — automatic under jit when the
                     loss is computed from fsdp-sharded params.
- stage 3 (p_g_os):  params themselves sharded (gather-on-use), the
                     reference's segment_size threshold becomes min_size.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import mesh as mesh_lib
from ..nn.module import Layer
from .fleet.meta_parallel import FSDP_MIN_SIZE, fsdp_rules

__all__ = ["group_sharded_parallel", "shard_optimizer_state", "save_group_sharded_model"]


def group_sharded_parallel(model: Layer, optimizer, level: str = "p_g_os",
                           scaler=None, group=None, offload: bool = False,
                           sync_buffers: bool = True, buffer_max_size: int = 2 ** 23,
                           segment_size: int = FSDP_MIN_SIZE, sync_comm: bool = False,
                           mesh: Mesh | None = None, axis: str = "fsdp"):
    """Apply a ZeRO stage to (model, optimizer) (parity: group_sharded.py:40).

    Returns (model, optimizer, scaler) like the reference.
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("group_sharded_parallel requires an active mesh")
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"unknown sharding level {level!r}")
    host_kind = _host_memory_kind()
    if offload and host_kind is None:
        raise NotImplementedError(
            "offload=True requires a backend exposing a host memory space "
            "(pinned_host on TPU/GPU PJRT, unpinned_host on jax CPU); this "
            "backend reports none")
    params = model.param_dict()
    if level == "p_g_os":
        specs = fsdp_rules(params, axis=axis, min_size=segment_size)
        new = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
               for k, v in params.items()}
        model.set_state_dict(new)
        for k, s in specs.items():
            mod, leaf = model._resolve(k)
            mod.set_param_spec(leaf, tuple(s))
        if offload:
            optimizer._state_sharding = {
                k: NamedSharding(mesh, specs[k], memory_kind=host_kind)
                for k, v in params.items()}
            _patch_optimizer_state_sharding(optimizer)
    else:
        # os / os_g: params stay replicated; mark the intended opt-state
        # sharding so init_state places slots sharded. offload additionally
        # parks the slots (master weights + moments) in pinned host memory —
        # the reference's GroupShardedStage3 offload (group_sharded_stage3.py
        # keeps master weights on CPU), expressed via PJRT memory kinds;
        # XLA streams them in for the update.
        optimizer._state_sharding = {
            k: NamedSharding(
                mesh,
                fsdp_rules({k: v}, axis=axis, min_size=segment_size)[k],
                memory_kind=host_kind if offload else None)
            for k, v in params.items()
        }
        _patch_optimizer_state_sharding(optimizer)
    return model, optimizer, scaler


def _host_memory_kind() -> str | None:
    """The backend's host memory space name, or None if it has none.
    TPU/GPU PJRT backends call it "pinned_host"; the jax CPU backend
    (which models host offload for tests) calls it "unpinned_host" —
    matching on the literal "pinned_host" alone broke offload there."""
    try:
        kinds = [m.kind for m in jax.devices()[0].addressable_memories()]
    except Exception:
        return None
    for kind in ("pinned_host", "unpinned_host"):
        if kind in kinds:
            return kind
    return None


def _patch_optimizer_state_sharding(optimizer):
    orig_init = optimizer.init_state

    def init_state(params):
        state = orig_init(params)
        shardings = getattr(optimizer, "_state_sharding", None)
        if not shardings:
            return state
        for slot in optimizer.slots:
            state[slot] = {k: jax.device_put(v, shardings[k])
                           for k, v in state[slot].items()}
        if "master" in state:
            state["master"] = {
                k: (jax.device_put(v, shardings[k]) if v is not None else None)
                for k, v in state["master"].items()}
        return state

    optimizer.init_state = init_state


def shard_optimizer_state(opt_state: dict, mesh: Mesh, axis: str = "fsdp",
                          min_size: int = FSDP_MIN_SIZE) -> dict:
    """Reshard an existing optimizer state dict onto the fsdp axis (ZeRO-1)."""
    def place(v):
        if not isinstance(v, jax.Array) or v.ndim == 0 or v.size < min_size:
            return v
        dim = int(np.argmax(v.shape))
        spec = [None] * v.ndim
        spec[dim] = axis
        return jax.device_put(v, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(place, opt_state)


def save_group_sharded_model(model, output, optimizer=None):
    """Parity: sharding.save_group_sharded_model — gather then save."""
    from ..framework.io import save
    from .auto_parallel_api import unshard_dtensor
    state = {k: unshard_dtensor(v) for k, v in model.state_dict().items()}
    save(state, output if output.endswith(".pdparams") else output + ".pdparams")
    if optimizer is not None and getattr(optimizer, "_eager_state", None) is not None:
        save(jax.tree.map(lambda x: x, optimizer._eager_state),
             output + ".pdopt")
