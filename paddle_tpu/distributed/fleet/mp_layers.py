"""Tensor-parallel layers (parity: fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:334, RowParallelLinear:541,
ParallelCrossEntropy:742 — and mp_ops.py collective wrappers).

TPU-native: the math is the plain layer; parallelism is a weight
PartitionSpec + activation sharding constraints, compiled by GSPMD into the
same allreduce/allgather pattern the reference launches by hand. The
``gather_output`` / ``input_is_parallel`` knobs become sharding constraints
on the activations. Explicit shard_map variants of the collective ops are in
distributed.collective for hand-scheduled code.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core import mesh as mesh_lib
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.module import Layer, Parameter

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
           "ParallelCrossEntropy", "mark_sharding", "manual_mp_region",
           "current_manual_mp"]

# Manual-mp trace flag (the mp twin of sequence_parallel's manual-sep
# region): inside a shard_map over the mp axis GSPMD is out of the
# picture, so model code must issue its own collectives — one psum after
# each row-parallel matmul, a masked lookup + psum for the vocab-parallel
# embedding, one all_gather on the vocab-sharded logits. Layers check
# ``current_manual_mp() == cfg.mp_axis`` to switch from sharding hints to
# those explicit collectives (serving/parallel.py wraps the engine's two
# step programs in this region).
_MANUAL_MP: list[str | None] = [None]


@contextlib.contextmanager
def manual_mp_region(axis: str | None):
    """Mark the current trace as running INSIDE a shard_map over ``axis``
    (manual mode): per-shard shapes, explicit collectives."""
    prev = _MANUAL_MP[0]
    _MANUAL_MP[0] = axis
    try:
        yield
    finally:
        _MANUAL_MP[0] = prev


def current_manual_mp() -> str | None:
    """The manual-mp axis name when tracing inside a shard_map region
    entered via :func:`manual_mp_region`, else None."""
    return _MANUAL_MP[0]


def mark_sharding(x, *spec):
    """with_sharding_constraint against the current mesh (no-op without one)."""
    mesh = mesh_lib.current_mesh()
    if mesh is None:
        return x
    try:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))
    except ValueError:
        return x  # outside jit with mismatched mesh


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded on mp. GSPMD turns the gather
    into local-lookup + allreduce exactly like the reference's masked lookup
    + mp_allreduce (mp_layers.py:47)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None, axis="mp"):
        super().__init__()
        init = weight_attr if callable(weight_attr) else I.Normal(0.0, 0.02)
        self.weight = Parameter(init((num_embeddings, embedding_dim), self._dtype),
                                spec=(axis, None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """Linear with output-dim sharded on mp (parity: mp_layers.py:334).

    ``gather_output=True`` adds a constraint forcing the output replicated
    (allgather); False leaves it mp-sharded for a following RowParallel.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, axis="mp"):
        super().__init__()
        self.axis = axis
        self.gather_output = gather_output
        init = weight_attr if callable(weight_attr) else I.XavierNormal()
        self.weight = Parameter(init((in_features, out_features), self._dtype),
                                spec=(None, axis))
        if has_bias:
            self.bias = Parameter(I.Constant(0.0)((out_features,), self._dtype),
                                  spec=(axis,))
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = mark_sharding(y, *([None] * y.ndim))
        else:
            y = mark_sharding(y, *([None] * (y.ndim - 1)), self.axis)
        return y


class RowParallelLinear(Layer):
    """Linear with input-dim sharded on mp (parity: mp_layers.py:541).
    The partial-sum allreduce the reference issues explicitly is inserted by
    GSPMD when the output constraint is replicated."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None, axis="mp"):
        super().__init__()
        self.axis = axis
        self.input_is_parallel = input_is_parallel
        init = weight_attr if callable(weight_attr) else I.XavierNormal()
        self.weight = Parameter(init((in_features, out_features), self._dtype),
                                spec=(axis, None))
        if has_bias:
            self.bias = Parameter(I.Constant(0.0)((out_features,), self._dtype))
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = mark_sharding(x, *([None] * (x.ndim - 1)), self.axis)
        y = x @ self.weight
        y = mark_sharding(y, *([None] * y.ndim))
        if self.bias is not None:
            y = y + self.bias
        return y


class ParallelCrossEntropy(Layer):
    """Cross entropy over vocab-sharded logits (parity: mp_layers.py:742 /
    c_softmax_with_cross_entropy). Under GSPMD the standard cross_entropy on
    mp-sharded logits compiles to the same two-collective pattern (max + sum
    over the vocab axis); this class exists for API parity."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
