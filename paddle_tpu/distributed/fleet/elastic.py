"""Elastic training manager (parity: fleet/elastic/manager.py:124
ElasticManager — etcd host registry, fault watching, np range scaling,
rendezvous reset + relaunch).

TPU-native scope: on TPU pods membership is fixed by the slice topology, so
"elastic" means **checkpoint-restart**: detect death (launcher), gang
restart (launch --max_restarts), resume from the newest checkpoint
(``ElasticManager.latest_checkpoint``). The etcd registry collapses to the
launcher's process table; np scale-in-range is not meaningful on a fixed
slice and is intentionally not implemented (documented deviation).
"""

from __future__ import annotations

import os
import re

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Worker-side elastic helper: restart-epoch awareness + checkpoint
    discovery, the two things a training script needs to survive a gang
    restart."""

    def __init__(self, checkpoint_dir: str | None = None):
        self.checkpoint_dir = checkpoint_dir
        self.restart_epoch = int(os.environ.get("PADDLE_RESTART_EPOCH", "0"))
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    @property
    def is_restart(self) -> bool:
        return self.restart_epoch > 0

    def latest_checkpoint(self) -> str | None:
        """Newest step-numbered checkpoint under checkpoint_dir (files or
        dirs named ``step_<n>`` / ``<n>`` / ``*-<n>``), or None."""
        d = self.checkpoint_dir
        if not d or not os.path.isdir(d):
            return None
        best, best_n = None, -1
        for name in os.listdir(d):
            m = re.search(r"(\d+)", name)
            if m and int(m.group(1)) > best_n:
                best, best_n = os.path.join(d, name), int(m.group(1))
        return best
