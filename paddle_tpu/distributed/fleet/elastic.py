"""Elastic training manager (parity: fleet/elastic/manager.py:124
ElasticManager — etcd host registry, fault watching, np range scaling,
rendezvous reset + relaunch).

TPU-native scope: on TPU pods membership is fixed by the slice topology, so
"elastic" means **checkpoint-restart**: detect death (launcher), gang
restart (launch --max_restarts), resume from the newest checkpoint
(``ElasticManager.latest_checkpoint``). The etcd registry collapses to the
launcher's process table; np scale-in-range is not meaningful on a fixed
slice and is intentionally not implemented (documented deviation).
"""

from __future__ import annotations

import os
import re

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Worker-side elastic helper: restart-epoch awareness + checkpoint
    discovery, the two things a training script needs to survive a gang
    restart."""

    def __init__(self, checkpoint_dir: str | None = None):
        self.checkpoint_dir = checkpoint_dir
        self.restart_epoch = int(os.environ.get("PADDLE_RESTART_EPOCH", "0"))
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    @property
    def is_restart(self) -> bool:
        return self.restart_epoch > 0

    def latest_checkpoint(self, gc_torn: bool = False) -> str | None:
        """Newest step-numbered COMMITTED checkpoint under checkpoint_dir
        (files or dirs named ``step_<n>`` / ``<n>`` / ``*-<n>``), or None.

        A resume must never come from a torn save, so entries are filtered
        through the checkpoint commit protocol (RESILIENCE.md): ``*.tmp``
        staging dirs and directories without a ``COMMIT`` marker /
        ``metadata.pkl`` are skipped — this is what makes a crash mid-save
        recoverable instead of poisoning the restart. Incidental
        digit-bearing files (logs, loss traces) are skipped the same way.
        With ``gc_torn=True`` leftover ``*.tmp`` staging dirs are deleted
        while scanning (safe on the restart path: any in-flight save died
        with the previous incarnation of this gang)."""
        from ..checkpoint.save_load import is_committed
        d = self.checkpoint_dir
        if not d or not os.path.isdir(d):
            return None
        best, best_n = None, -1
        for name in os.listdir(d):
            full = os.path.join(d, name)
            if name.endswith(".tmp"):  # torn staging, never a candidate
                if gc_torn and os.path.isdir(full):
                    import shutil
                    shutil.rmtree(full, ignore_errors=True)
                continue
            # the step number must be a separator-delimited FINAL component
            # (one extension allowed), so "loss_e12.txt" / "run3_log" don't
            # outrank real checkpoints
            m = re.search(r"(?:^|[-_.])(\d+)(?:\.[A-Za-z0-9]+)?$", name)
            if not m or int(m.group(1)) <= best_n:
                continue
            if not is_committed(full):
                continue
            best, best_n = full, int(m.group(1))
        return best
