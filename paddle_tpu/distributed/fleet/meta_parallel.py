"""Hybrid-parallel model wrappers (parity: fleet/meta_parallel/).

The reference wraps models in PipelineParallel/TensorParallel/ShardingParallel
classes that install communication hooks. TPU-native equivalent: annotate
parameter shardings (mp/fsdp axes) on the existing Layer tree and let GSPMD
place collectives; pipeline parallelism has its own explicit scheduler in
distributed/pipeline.py.
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...nn.module import Layer

__all__ = ["apply_hybrid_shardings", "fsdp_rules", "TensorParallel",
           "ShardingParallel", "SegmentParallel"]

# Minimum parameter size worth sharding on the fsdp axis — the analogue of
# GroupShardedStage3's segment_size=2^20 threshold (SURVEY §B.2).
FSDP_MIN_SIZE = 2 ** 20


def fsdp_rules(params: dict[str, jax.Array], axis: str = "fsdp",
               min_size: int = FSDP_MIN_SIZE) -> dict[str, PartitionSpec]:
    """Shard the largest dim of each big param on the fsdp axis."""
    specs = {}
    for k, v in params.items():
        if v.size >= min_size and v.ndim >= 1:
            dim = int(np.argmax(v.shape))
            entries = [None] * v.ndim
            entries[dim] = axis
            specs[k] = PartitionSpec(*entries)
        else:
            specs[k] = PartitionSpec()
    return specs


def apply_hybrid_shardings(model: Layer, mesh: Mesh, strategy=None) -> Layer:
    """Place every param with its layer-declared spec (mp/TP), then overlay
    fsdp sharding for large unsharded params. Degrees of 1 make the axes
    vanish (PartitionSpec entries over size-1 axes are no-ops)."""
    params = model.param_dict()
    declared = model.spec_dict()
    fsdp = fsdp_rules({k: v for k, v in params.items()
                       if not declared.get(k)})
    new = {}
    for k, v in params.items():
        spec = declared.get(k)
        pspec = PartitionSpec(*spec) if spec else fsdp.get(k, PartitionSpec())
        new[k] = jax.device_put(v, NamedSharding(mesh, pspec))
    model.set_state_dict(new)
    # buffers replicate
    bufs = model.buffer_dict()
    if bufs:
        rep = {k: jax.device_put(v, NamedSharding(mesh, PartitionSpec()))
               for k, v in bufs.items()}
        model.set_state_dict(rep)
    return model


class _Passthrough(Layer):
    def __init__(self, layers: Layer):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kw):
        return self._layers(*args, **kw)


class TensorParallel(_Passthrough):
    """Parity shim: TP is expressed by layer weight_specs (ColumnParallelLinear
    == Linear(weight_spec=(None,'mp')))."""


class ShardingParallel(_Passthrough):
    pass


class SegmentParallel(_Passthrough):
    pass
