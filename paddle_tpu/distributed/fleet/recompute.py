"""Activation recomputation (parity: fleet/recompute/recompute.py:108 —
PyLayer-based checkpointing with RNG-state restore).

TPU-native: jax.checkpoint (remat) — XLA re-runs the wrapped segment in the
backward instead of storing activations; RNG correctness is automatic because
stochastic layers draw from counter-derived keys (core/rng.py), which remat
replays identically. Policies map paddle's selective-recompute knobs onto
jax.checkpoint_policies.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax

__all__ = ["recompute", "recompute_sequential", "no_recompute",
           "RECOMPUTE_POLICIES"]

RECOMPUTE_POLICIES = {
    "full": None,  # save nothing, recompute all
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
}


def recompute(function: Callable, *args, use_reentrant: bool = True,
              policy: str | None = None, **kwargs):
    """Run ``function(*args)`` under remat (parity: paddle
    distributed.fleet.recompute / paddle.distributed.recompute)."""
    pol = RECOMPUTE_POLICIES.get(policy) if isinstance(policy, str) else policy
    return jax.checkpoint(function, policy=pol)(*args, **kwargs)


def recompute_sequential(ctx: dict | None, functions: Sequence[Callable] | Callable,
                         *args, **kwargs):
    """Checkpoint a Sequential-like chain segment-by-segment (parity:
    recompute_sequential). ``ctx`` may carry {'segments': N}."""
    segments = (ctx or {}).get("segments", 1)
    if callable(functions) and hasattr(functions, "_sub_layers"):
        layers = list(functions._sub_layers.values())
    else:
        layers = list(functions)
    n = len(layers)
    per = max(1, n // max(1, segments))
    x = args[0] if len(args) == 1 else args

    def run_segment(seg, x):
        for l in seg:
            x = l(x)
        return x

    i = 0
    while i < n:
        seg = layers[i:i + per]
        x = jax.checkpoint(functools.partial(run_segment, seg))(x)
        i += per
    return x


def no_recompute(fn: Callable) -> Callable:
    """Mark a function's outputs as saveable inside an enclosing remat."""
    return fn
