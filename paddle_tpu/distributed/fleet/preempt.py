"""Preemption-aware shutdown (parity: the elastic manager's graceful-exit
path in fleet/elastic/manager.py, reshaped for TPU maintenance events).

On TPU pods a planned preemption arrives as SIGTERM with a grace window.
A trainer that ignores it loses everything since its last checkpoint; a
trainer that checkpoints *inside the signal handler* corrupts state (the
handler interrupts arbitrary code, possibly mid-save). The contract here is
the standard cooperative one:

- the signal handler only sets a flag;
- the training loop polls :meth:`PreemptionGuard.preempted` once per step
  (cheap: one Event check) and, when set, calls
  :meth:`PreemptionGuard.drain_and_exit` — which drains any in-flight
  ``AsyncSaveHandle`` (so a half-written async checkpoint is completed and
  committed, not torn), takes a final synchronous checkpoint via the
  caller's ``save_fn``, and exits with :data:`EXIT_PREEMPTED`.

The launcher (distributed/launch/main.py) forwards SIGTERM to every worker
and recognizes :data:`EXIT_PREEMPTED` as a clean preemption rather than a
crash when classifying exits.
"""

from __future__ import annotations

import os
import signal
import sys
import threading

__all__ = ["PreemptionGuard", "EXIT_PREEMPTED"]

# 128 + SIGTERM(15): the conventional "terminated by SIGTERM" code, reused
# deliberately so ordinary process supervisors also read it as a clean stop.
EXIT_PREEMPTED = 143


class PreemptionGuard:
    """Install SIGTERM (and optionally other) handlers that request a
    cooperative shutdown of the training loop.

    Usage::

        guard = PreemptionGuard()
        for step in range(start, total):
            train_step(...)
            save_state_dict(state, f"{ckpt}/step_{step}", async_save=True)
            if guard.preempted:
                guard.drain_and_exit(
                    save_fn=lambda: save_state_dict(
                        state, f"{ckpt}/step_{step}_final"))
    """

    def __init__(self, signals=(signal.SIGTERM,),
                 exit_code: int = EXIT_PREEMPTED):
        self.exit_code = exit_code
        self._event = threading.Event()
        self._prev = {}
        for sig in signals:
            # only the main thread may set signal handlers; a guard built
            # on a worker thread degrades to a manually-triggered flag
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                break

    def _on_signal(self, signum, frame):
        # handler does the absolute minimum — the loop does the real work
        self._event.set()

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def request(self) -> None:
        """Programmatic preemption (tests, in-process schedulers)."""
        self._event.set()

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev.clear()

    def drain_and_exit(self, save_fn=None, drain_timeout: float = 600.0,
                       _exit=sys.exit) -> None:
        """Finish in-flight async saves, take the final checkpoint, exit.

        Order matters: drain FIRST (an async save racing the final sync
        save to the same directory tree would corrupt both), then the
        final synchronous ``save_fn``, then exit with the distinct
        preemption code so the launcher never counts this as a crash."""
        from ..checkpoint.save_load import drain_inflight_saves
        drain_errs = drain_inflight_saves(timeout=drain_timeout)
        for path, err in drain_errs:
            print(f"[preempt] async save to {path!r} failed while draining: "
                  f"{err!r}", file=sys.stderr)
        if save_fn is not None:
            save_fn()
        sys.stderr.flush()
        sys.stdout.flush()
        self.uninstall()
        _exit(self.exit_code)

    def check(self, save_fn=None, drain_timeout: float = 600.0) -> None:
        """One-liner for training loops: no-op until preempted, then runs
        the full drain → final save → exit sequence."""
        if self.preempted:
            print(f"[preempt] SIGTERM received (rank "
                  f"{os.environ.get('PADDLE_TRAINER_ID', '0')}): draining "
                  f"saves and taking final checkpoint", file=sys.stderr)
            self.drain_and_exit(save_fn=save_fn, drain_timeout=drain_timeout)
