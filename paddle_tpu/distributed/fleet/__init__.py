"""Fleet facade (parity: python/paddle/distributed/fleet/ — fleet.init:167,
distributed_model model.py:32, distributed_optimizer fleet.py:1302,
DistributedStrategy distributed_strategy.py:175).

The strategy object declares parallel degrees; ``init`` builds one hybrid
Mesh; model/optimizer wrappers attach shardings instead of rewriting graphs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax

from ...core import mesh as mesh_lib
from ...nn.module import Layer

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num", "HybridCommunicateGroup"]


@dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1


@dataclass
class DistributedStrategy:
    """Declarative parallel config (parity: proto-backed DistributedStrategy).
    Only TPU-meaningful knobs are kept; unknown attribute writes are accepted
    and ignored (the reference has ~80 flags, most CUDA-specific)."""

    hybrid_configs: dict = field(default_factory=dict)
    amp: bool = False
    amp_configs: dict = field(default_factory=dict)
    recompute: bool = False
    recompute_configs: dict = field(default_factory=dict)
    gradient_merge: bool = False
    gradient_merge_configs: dict = field(default_factory=dict)
    sharding: bool = False
    sharding_configs: dict = field(default_factory=dict)
    pipeline: bool = False
    pipeline_configs: dict = field(default_factory=dict)
    tensor_parallel: bool = False
    tensor_parallel_configs: dict = field(default_factory=dict)
    find_unused_parameters: bool = False

    def hybrid(self) -> HybridConfig:
        hc = self.hybrid_configs or {}
        return HybridConfig(
            dp_degree=hc.get("dp_degree", 1),
            mp_degree=hc.get("mp_degree", 1),
            pp_degree=hc.get("pp_degree", 1),
            sharding_degree=hc.get("sharding_degree", 1),
            sep_degree=hc.get("sep_degree", 1),
        )


class HybridCommunicateGroup(mesh_lib.HybridTopology):
    """Parity: fleet/base/topology.py:178 — rank/size per axis over the Mesh."""

    def get_model_parallel_world_size(self):
        return self.mp_degree

    def get_data_parallel_world_size(self):
        return self.dp_degree

    def get_pipe_parallel_world_size(self):
        return self.pp_degree

    def get_sharding_parallel_world_size(self):
        return self.sharding_degree

    def get_sep_parallel_world_size(self):
        return self.sep_degree


_state: dict = {"strategy": None, "hcg": None, "mesh": None}


def init(role_maker=None, is_collective: bool = True,
         strategy: DistributedStrategy | None = None):
    """Build the hybrid mesh from the strategy's degrees.

    dp is outermost (cross-host/DCN friendly), mp innermost (ICI-bandwidth
    hungry) — the same ordering the reference fixes in CommunicateTopology.
    """
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid()
    degrees = {"dp": hc.dp_degree, "pp": hc.pp_degree, "fsdp": hc.sharding_degree,
               "sep": hc.sep_degree, "mp": hc.mp_degree}
    n_needed = 1
    for v in degrees.values():
        n_needed *= v
    n_dev = jax.device_count()
    if n_needed == 1:
        degrees["dp"] = n_dev  # default pure-DP over all devices
    elif n_needed < n_dev and n_dev % n_needed == 0:
        degrees["dp"] *= n_dev // n_needed
    mesh = mesh_lib.make_mesh(degrees)
    _state["strategy"] = strategy
    _state["mesh"] = mesh
    _state["hcg"] = HybridCommunicateGroup(mesh)
    mesh_lib._current_mesh[0] = mesh
    return _state["hcg"]


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _state["hcg"] is None:
        init()
    return _state["hcg"]


def fleet_mesh():
    return _state["mesh"]


def worker_index():
    return jax.process_index()


def worker_num():
    return jax.process_count()


def distributed_model(model: Layer) -> Layer:
    """Attach shardings per strategy (parity: fleet/model.py:32 which wraps in
    PipelineParallel/TensorParallel/ShardingParallel/DataParallel by degree)."""
    from .meta_parallel import apply_hybrid_shardings
    if _state["hcg"] is None:
        init()
    return apply_hybrid_shardings(model, _state["mesh"], _state["strategy"])


def distributed_optimizer(optimizer, strategy: DistributedStrategy | None = None):
    """Parity: HybridParallelOptimizer — on TPU the optimizer is already
    sharding-agnostic (opt state inherits param shardings = ZeRO-1); grad
    clip over the global norm is correct because XLA reduces over all axes."""
    return optimizer
