"""Tiny helper: is any multi-device mesh active? Used to gate Pallas kernels
(which carry no GSPMD sharding rule) onto the single-device path."""

from __future__ import annotations


def no_mesh_active() -> bool:
    from .core import mesh as mesh_lib
    m = mesh_lib.current_mesh()
    return m is None or all(s == 1 for s in m.shape.values())
