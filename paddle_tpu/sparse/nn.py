"""Sparse nn layers (parity: python/paddle/sparse/nn/ — activations,
Softmax, BatchNorm over sparse values, Conv3D/SubmConv3D, MaxPool3D).

TPU lowering note: XLA/MXU has no gather-based sparse conv kernel that
beats dense compute at the occupancies these layers see in practice, so
the conv/pool layers lower through a dense window (a measured-parity
collapse in the SURVEY §7 sense); SubmConv3D re-masks the output to the
input's coordinate set, which is its defining semantic. BatchNorm,
activations, and Softmax operate directly on the stored values — no
densify."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..nn import initializer as I
from ..nn.module import Layer, Parameter
from . import is_sparse_coo, is_sparse_csr, relu as _relu
from . import to_dense, to_sparse_coo

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv3D", "SubmConv3D", "MaxPool3D"]


class ReLU(Layer):
    def forward(self, x):
        return _relu(x)


class ReLU6(Layer):
    def forward(self, x):
        from . import _unary
        return _unary(lambda v: jnp.clip(v, 0.0, 6.0))(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from . import _unary
        return _unary(lambda v: jnp.where(v >= 0, v,
                                          v * self.negative_slope))(x)


class Softmax(Layer):
    """Softmax over the last axis of a 2D sparse tensor, computed per
    row over the STORED values only (parity: sparse/nn Softmax —
    implicit zeros do not participate)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse Softmax supports axis=-1")

    def forward(self, x):
        if is_sparse_csr(x):
            rows = jnp.repeat(jnp.arange(len(x.indptr) - 1),
                              jnp.diff(x.indptr),
                              total_repeat_length=x.data.shape[0])
            data = x.data
            n = len(x.indptr) - 1
        elif is_sparse_coo(x):
            if x.ndim != 2:
                raise ValueError("sparse Softmax expects a 2D tensor")
            rows = x.indices[:, 0]
            data = x.data
            n = x.shape[0]
        else:
            return jax.nn.softmax(jnp.asarray(x), axis=-1)
        mx = jax.ops.segment_max(data, rows, n)
        e = jnp.exp(data - mx[rows])
        z = jax.ops.segment_sum(e, rows, n)
        out = e / z[rows]
        if is_sparse_csr(x):
            return jsparse.BCSR((out, x.indices, x.indptr), shape=x.shape)
        return jsparse.BCOO((out, x.indices), shape=x.shape)


class BatchNorm(Layer):
    """BatchNorm over sparse values with channels last (parity:
    sparse/nn BatchNorm: input [N, ..., C] sparse, stats over nnz)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError("sparse layers are channels-last: "
                             "data_format must be 'NDHWC'")
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        w_init = weight_attr if callable(weight_attr) else I.Constant(1.0)
        b_init = bias_attr if callable(bias_attr) else I.Constant(0.0)
        self.weight = Parameter(w_init((num_features,), self._dtype))
        self.bias = Parameter(b_init((num_features,), self._dtype))
        self.register_buffer("_mean", jnp.zeros((num_features,)))
        self.register_buffer("_variance", jnp.ones((num_features,)))

    def forward(self, x):
        C = self.num_features
        if is_sparse_csr(x):
            raise ValueError(
                "sparse BatchNorm supports COO or dense inputs (reference "
                "contract: SparseCooTensor)")
        if is_sparse_coo(x) and x.data.ndim == 1:
            # fully-sparse layout: the channel coordinate is the LAST
            # index column; per-channel stats via segment reductions
            ch = x.indices[:, -1]
            vals = x.data
            if self.training:
                raw_cnt = jax.ops.segment_sum(jnp.ones_like(vals), ch, C)
                cnt = jnp.maximum(raw_cnt, 1.0)
                mean = jax.ops.segment_sum(vals, ch, C) / cnt
                var = jax.ops.segment_sum(
                    (vals - mean[ch]) ** 2, ch, C) / cnt
                m = self.momentum
                # channels absent from this batch keep their running
                # stats (blending in 0/0 would decay variance to zero)
                occupied = raw_cnt > 0
                self._mean = jnp.where(
                    occupied, m * self._mean + (1 - m) * mean, self._mean)
                self._variance = jnp.where(
                    occupied, m * self._variance + (1 - m) * var,
                    self._variance)
            else:
                mean, var = self._mean, self._variance
            out = (vals - mean[ch]) / jnp.sqrt(var[ch] + self.epsilon) \
                * self.weight[ch] + self.bias[ch]
            return jsparse.BCOO((out, x.indices), shape=x.shape)
        vals = x.data if is_sparse_coo(x) else jnp.asarray(x)
        # channels-last: stats over every axis but the channel one
        flat = vals.reshape(-1, vals.shape[-1])
        if self.training:
            mean = flat.mean(axis=0)
            var = flat.var(axis=0)
            m = self.momentum
            self._mean = m * self._mean + (1 - m) * mean
            self._variance = m * self._variance + (1 - m) * var
        else:
            mean, var = self._mean, self._variance
        out = (vals - mean) / jnp.sqrt(var + self.epsilon) * self.weight \
            + self.bias
        if is_sparse_coo(x):
            return jsparse.BCOO((out, x.indices), shape=x.shape)
        return out


class SyncBatchNorm(BatchNorm):
    """Parity: sparse/nn SyncBatchNorm — under GSPMD the batch stats are
    already global (XLA all-reduces the mean/var contractions), so the
    sync variant is the same layer."""


def _to3(v):
    return (v,) * 3 if isinstance(v, int) else tuple(v)


def _dense_conv3d(xd, weight, bias, stride, padding, dilation, groups):
    # channels-last [N, D, H, W, C]; weight [kd, kh, kw, Cin/g, Cout]
    dn = jax.lax.conv_dimension_numbers(
        xd.shape, weight.shape, ("NDHWC", "DHWIO", "NDHWC"))
    if isinstance(padding, str):
        pad = padding
    else:
        pad = [p if isinstance(p, tuple) else (p, p)
               for p in (_to3(padding) if not (
                   isinstance(padding, tuple)
                   and padding and isinstance(padding[0], tuple))
                   else padding)]
    out = jax.lax.conv_general_dilated(
        xd, weight, window_strides=_to3(stride), padding=pad,
        rhs_dilation=_to3(dilation), dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        out = out + bias
    return out


class Conv3D(Layer):
    """Parity: sparse/nn Conv3D — sparse [N, D, H, W, C] input."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError("sparse layers are channels-last: "
                             "data_format must be 'NDHWC'")
        k = _to3(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        fan_in = (in_channels // groups) * k[0] * k[1] * k[2]
        w_init = weight_attr if callable(weight_attr) else \
            I.KaimingUniform(fan_in=fan_in)
        self.weight = Parameter(
            w_init(k + (in_channels // groups, out_channels), self._dtype))
        if bias_attr is False:
            self.bias = None
        else:
            b_init = bias_attr if callable(bias_attr) else I.Constant(0.0)
            self.bias = Parameter(b_init((out_channels,), self._dtype))

    def forward(self, x):
        out = _dense_conv3d(to_dense(x), self.weight, self.bias,
                            self.stride, self.padding, self.dilation,
                            self.groups)
        return to_sparse_coo(out)


class SubmConv3D(Conv3D):
    """Submanifold conv: the output's coordinate set is restricted to the
    input's active sites (stride 1) — no sparsity dilation, the property
    that makes deep sparse CNNs viable (parity: sparse/nn SubmConv3D over
    the reference's rulebook kernels). Known deviation: the dense-window
    lowering re-sparsifies by value, so an active site whose OUTPUT is
    exactly zero in every channel is not stored (the rulebook kernel
    would keep it as a stored zero); with float conv outputs this is
    measure-zero in practice."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 key=None):
        if max(_to3(stride)) != 1:
            raise ValueError("SubmConv3D requires stride 1")
        k = _to3(kernel_size)
        super().__init__(in_channels, out_channels, kernel_size,
                         stride=1, padding=0, dilation=dilation,
                         groups=groups, weight_attr=weight_attr,
                         bias_attr=bias_attr)
        # the output must cover the input's coordinate set exactly, so
        # padding is size-preserving by construction (asymmetric for even
        # kernels); a user-supplied padding value is ignored — the
        # rulebook keeps active sites regardless of it
        d = _to3(dilation)
        self.padding = tuple(
            (((kk - 1) * dd) // 2, ((kk - 1) * dd + 1) // 2)
            for kk, dd in zip(k, d))

    def forward(self, x):
        if not is_sparse_coo(x):
            raise ValueError("SubmConv3D expects a sparse COO input")
        xd = to_dense(x)
        out = _dense_conv3d(xd, self.weight, self.bias, 1, self.padding,
                            self.dilation, self.groups)
        # active-site mask from the STORED COORDINATES, not the values —
        # a stored zero (e.g. post-ReLU) is still an active site and the
        # rulebook contract preserves it
        n_spatial = 4  # N, D, H, W of the NDHWC layout
        sp = x.indices[:, :min(x.indices.shape[1], n_spatial)]
        active = jnp.zeros(x.shape[:sp.shape[1]], bool)
        active = active.at[tuple(sp[:, i] for i in range(sp.shape[1]))] \
            .set(True)
        active = active.reshape(active.shape + (1,) * (out.ndim
                                                       - active.ndim))
        return to_sparse_coo(out * active)


class MaxPool3D(Layer):
    """Parity: sparse/nn MaxPool3D over sparse [N, D, H, W, C]."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError("sparse layers are channels-last: "
                             "data_format must be 'NDHWC'")
        if return_mask:
            raise NotImplementedError(
                "sparse MaxPool3D does not materialize argmax indices "
                "(no sparse unpool in the reference either)")
        self.kernel = _to3(kernel_size)
        self.stride = _to3(stride if stride is not None else kernel_size)
        self.padding = _to3(padding)
        self.ceil_mode = ceil_mode

    def forward(self, x):
        xd = to_dense(x)
        pads = [list((p, p)) for p in self.padding]
        if self.ceil_mode:
            # extend the high side so the last partial window pools too
            for d in range(3):
                size = xd.shape[1 + d] + 2 * self.padding[d]
                span = size - self.kernel[d]
                out_d = -(-span // self.stride[d]) + 1
                pads[d][1] += (out_d - 1) * self.stride[d] \
                    + self.kernel[d] - size
        out = jax.lax.reduce_window(
            xd, -jnp.inf, jax.lax.max,
            window_dimensions=(1, *self.kernel, 1),
            window_strides=(1, *self.stride, 1),
            padding=((0, 0), *[tuple(p) for p in pads], (0, 0)))
        out = jnp.where(jnp.isfinite(out), out, 0.0)
        return to_sparse_coo(out)
