"""Sparse nn layers (parity: python/paddle/sparse/nn/ — activation layers
operating on sparse tensors)."""

from __future__ import annotations

from ..nn.module import Layer
from . import relu as _relu


class ReLU(Layer):
    def forward(self, x):
        return _relu(x)


__all__ = ["ReLU"]
