"""Sparse tensors (parity: python/paddle/sparse/ — COO/CSR creation,
conversion, unary/binary math, sparse @ dense matmul; backed by
phi SparseCoo/CsrTensor + sparse kernels in the reference).

TPU-native: jax.experimental.sparse BCOO/BCSR are the storage formats —
XLA compiles gather/scatter-based kernels; unary ops apply to the stored
values (preserving the zero-pattern contract of the reference's sparse
unary kernels)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "to_dense", "to_sparse_coo",
    "to_sparse_csr", "is_sparse", "is_sparse_coo", "is_sparse_csr",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "relu", "abs", "sin", "tanh", "sqrt", "square", "pow", "neg", "cast",
    "transpose", "sum", "nnz", "values", "indices",
]


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """COO tensor from [sparse_ndim, nnz] indices + [nnz] values (parity:
    paddle.sparse.sparse_coo_tensor)."""
    idx = jnp.asarray(indices)
    vals = jnp.asarray(values, dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    return jsparse.BCOO((vals, idx.T), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    return jsparse.BCSR((jnp.asarray(values, dtype), jnp.asarray(cols),
                         jnp.asarray(crows)), shape=tuple(shape))


def is_sparse(x):
    return isinstance(x, (jsparse.BCOO, jsparse.BCSR))


def is_sparse_coo(x):
    return isinstance(x, jsparse.BCOO)


def is_sparse_csr(x):
    return isinstance(x, jsparse.BCSR)


def to_dense(x):
    return x.todense() if is_sparse(x) else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim=None):
    if is_sparse_csr(x):
        return x.to_bcoo()
    return jsparse.BCOO.fromdense(jnp.asarray(x))


def to_sparse_csr(x):
    if is_sparse_coo(x):
        return jsparse.BCSR.from_bcoo(x)
    return jsparse.BCSR.fromdense(jnp.asarray(x))


def nnz(x):
    return x.nse


def values(x):
    return x.data


def indices(x):
    return x.indices.T if is_sparse_coo(x) else x.indices


# ---- elementwise (zero-preserving applied to values; parity:
# paddle/phi/kernels/sparse/unary_kernel.h) ----

def _unary(fn):
    def op(x, name=None):
        if is_sparse_coo(x):
            return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape)
        if is_sparse_csr(x):
            return jsparse.BCSR((fn(x.data), x.indices, x.indptr),
                                shape=x.shape)
        return fn(jnp.asarray(x))
    return op


relu = _unary(jax.nn.relu)
abs = _unary(jnp.abs)  # noqa: A001
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
neg = _unary(jnp.negative)


def pow(x, factor, name=None):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    if is_sparse_coo(x):
        return jsparse.BCOO(
            (x.data.astype(value_dtype) if value_dtype else x.data,
             x.indices.astype(index_dtype) if index_dtype else x.indices),
            shape=x.shape)
    return _unary(lambda v: v.astype(value_dtype))(x)


# ---- binary / matmul ----

def _coerce_pair(x, y):
    xd = to_dense(x)
    yd = to_dense(y)
    return xd, yd


def add(x, y, name=None):
    if is_sparse_coo(x) and is_sparse_coo(y):
        # concatenate index/value lists; duplicate coordinates sum on
        # densify (the COO semantics the reference's sparse add relies on)
        idx = jnp.concatenate([x.indices, y.indices], axis=0)
        val = jnp.concatenate([x.data, y.data], axis=0)
        return jsparse.BCOO((val, idx), shape=x.shape)
    xd, yd = _coerce_pair(x, y)
    return to_sparse_coo(xd + yd) if is_sparse(x) else xd + yd


def subtract(x, y, name=None):
    xd, yd = _coerce_pair(x, y)
    return to_sparse_coo(xd - yd) if is_sparse(x) else xd - yd


def multiply(x, y, name=None):
    xd, yd = _coerce_pair(x, y)
    return to_sparse_coo(xd * yd) if is_sparse(x) else xd * yd


def divide(x, y, name=None):
    xd, yd = _coerce_pair(x, y)
    return xd / yd


def matmul(x, y, name=None):
    """sparse @ dense (and sparse @ sparse via densify) — parity:
    paddle.sparse.matmul; BCOO dot_general compiles to gather+MXU."""
    if is_sparse(x) and not is_sparse(y):
        return x @ jnp.asarray(y)
    if is_sparse(x) and is_sparse(y):
        return to_sparse_coo(to_dense(x) @ to_dense(y))
    return jnp.asarray(x) @ to_dense(y)


def masked_matmul(x, y, mask, name=None):
    """Dense @ dense computed only at mask's nonzero positions (parity:
    paddle.sparse.masked_matmul; the SDDMM pattern)."""
    dense = jnp.asarray(x) @ jnp.asarray(y)
    m = mask if is_sparse_coo(mask) else to_sparse_coo(mask)
    rows, cols = m.indices[:, 0], m.indices[:, 1]
    return jsparse.BCOO((dense[rows, cols], m.indices), shape=dense.shape)


def transpose(x, perm, name=None):
    if is_sparse_coo(x):
        return jsparse.BCOO((x.data, x.indices[:, jnp.asarray(perm)]),
                            shape=tuple(np.asarray(x.shape)[list(perm)]))
    return jnp.transpose(to_dense(x), perm)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    vals = x.data if is_sparse(x) else jnp.asarray(x)
    if axis is None:
        out = jnp.sum(vals, dtype=dtype)
        return out[None] if keepdim else out
    return jnp.sum(to_dense(x), axis=axis, dtype=dtype, keepdims=keepdim)


from . import nn  # noqa: F401,E402  (after op definitions it depends on)


# ---- unary tail (parity: sparse/unary.py) ----

asin = _unary(jnp.arcsin)
asinh = _unary(jnp.arcsinh)
atan = _unary(jnp.arctan)
atanh = _unary(jnp.arctanh)
sinh = _unary(jnp.sinh)
tan = _unary(jnp.tan)
expm1 = _unary(jnp.expm1)
log1p = _unary(jnp.log1p)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)
isnan = _unary(jnp.isnan)


def coalesce(x, name=None):
    """Merge duplicate COO coordinates by summation (parity:
    sparse/unary.py coalesce)."""
    if not is_sparse_coo(x):
        raise ValueError("coalesce expects a sparse COO tensor")
    return x.sum_duplicates(remove_zeros=False)


def reshape(x, shape, name=None):
    """Parity: sparse/unary.py reshape — same storage format out."""
    if is_sparse_coo(x):
        return jsparse.bcoo_reshape(x, new_sizes=tuple(shape))
    if is_sparse_csr(x):
        return to_sparse_csr(jnp.reshape(to_dense(x), shape))
    return jnp.reshape(jnp.asarray(x), shape)


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    """Parity: sparse/unary.py slice."""
    import builtins
    d = to_dense(x)
    sl = [builtins.slice(None)] * d.ndim
    for ax, s, e in zip(axes, starts, ends):
        sl[ax] = builtins.slice(int(s), int(e))
    out = d[tuple(sl)]
    if is_sparse_coo(x):
        return to_sparse_coo(out)
    if is_sparse_csr(x):
        return to_sparse_csr(out)
    return out


def mv(x, vec, name=None):
    """sparse matrix @ dense vector (parity: sparse/binary.py mv)."""
    return x @ jnp.asarray(vec)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) (parity: sparse/multiary.py)."""
    prod = matmul(x, y)
    return beta * to_dense(input) + alpha * to_dense(prod)


__all__ += ["asin", "asinh", "atan", "atanh", "sinh", "tan", "expm1",
            "log1p", "rad2deg", "deg2rad", "isnan", "coalesce", "reshape",
            "slice", "mv", "is_same_shape", "addmm"]
