"""Model hub (parity: python/paddle/hapi/hub.py — list/help/load over a
``hubconf.py`` entry-point protocol).

Zero-egress environment: the ``github``/``gitee`` sources raise with the
archive URL for the user to fetch; ``source="local"`` (a directory
containing hubconf.py) is fully functional — the protocol, entry-point
discovery, dependency check, and kwargs forwarding match the reference.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_builtin_list = list


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_hubconf_{abs(hash(repo_dir))}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    deps = getattr(mod, "dependencies", [])
    missing = [d for d in deps
               if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(
            f"hub repo {repo_dir!r} requires missing packages: {missing}")
    return mod


def _resolve(repo, source):
    if source == "local":
        return _load_hubconf(repo)
    if source in ("github", "gitee"):
        raise RuntimeError(
            f"this environment has no network egress; clone "
            f"https://{source}.com/{repo} locally and call with "
            f"source='local'")
    raise ValueError(f"unknown source {source!r}: use local/github/gitee")


def list(repo_dir, source="github", force_reload=False):
    """Entry points exported by the repo's hubconf.py."""
    mod = _resolve(repo_dir, source)
    return _builtin_list(
        name for name in dir(mod)
        if callable(getattr(mod, name)) and not name.startswith("_")
        and name != "dependencies")


def help(repo_dir, model, source="github", force_reload=False):
    """Docstring of one entry point."""
    mod = _resolve(repo_dir, source)
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"no callable entry point {model!r}; "
                           f"available: {list(repo_dir, source)}")
    return entry.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Instantiate an entry point with kwargs."""
    mod = _resolve(repo_dir, source)
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"no callable entry point {model!r}; "
                           f"available: {list(repo_dir, source)}")
    return entry(**kwargs)
