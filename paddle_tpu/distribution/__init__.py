"""Probability distributions (parity: python/paddle/distribution/ — ~25
distributions, transforms, TransformedDistribution, Independent,
kl_divergence with a registry).

TPU-native: sampling uses explicit jax.random keys (the framework RNG
stream supplies one when omitted); log_prob/entropy are jnp compositions
that fuse under jit. Shapes follow the reference: ``batch_shape`` from
broadcast parameters, ``sample([n])`` prepends sample dims.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as _rng

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical", "Beta",
    "Gamma", "Dirichlet", "Exponential", "Laplace", "LogNormal", "Gumbel",
    "Geometric", "Cauchy", "StudentT", "Poisson", "Binomial", "Multinomial",
    "ContinuousBernoulli", "ExponentialFamily", "Independent",
    "MultivariateNormal",
    "TransformedDistribution", "kl_divergence", "register_kl",
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
    "TanhTransform", "AbsTransform", "PowerTransform", "ChainTransform",
]


def _key(key):
    return key if key is not None else _rng.next_key()


def _shape(sample_shape, batch_shape):
    return tuple(sample_shape) + tuple(batch_shape)


class Distribution:
    """Base (parity: distribution/distribution.py)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=(), key=None):
        return jax.lax.stop_gradient(self.rsample(shape, key=key))

    def rsample(self, shape=(), key=None):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, self.batch_shape)

    @property
    def variance(self):
        return jnp.broadcast_to(self.scale ** 2, self.batch_shape)

    @property
    def stddev(self):
        return jnp.broadcast_to(self.scale, self.batch_shape)

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return self.loc + self.scale * jax.random.normal(_key(key), s)

    def log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def cdf(self, value):
        return 0.5 * (1 + jax.scipy.special.erf(
            (value - self.loc) / (self.scale * math.sqrt(2))))

    def entropy(self):
        return jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return jnp.broadcast_to((self.low + self.high) / 2, self.batch_shape)

    @property
    def variance(self):
        return jnp.broadcast_to((self.high - self.low) ** 2 / 12,
                                self.batch_shape)

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        u = jax.random.uniform(_key(key), s)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low),
                                self.batch_shape)

    def cdf(self, value):
        return jnp.clip((value - self.low) / (self.high - self.low), 0, 1)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is None:
            self.logits = jnp.asarray(logits, jnp.float32)
            self.probs = jax.nn.sigmoid(self.logits)
        else:
            self.probs = jnp.asarray(probs, jnp.float32)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.bernoulli(_key(key), self.probs, s).astype(
            jnp.float32)

    def log_prob(self, value):
        v = jnp.asarray(value, jnp.float32)
        return v * jax.nn.log_sigmoid(self.logits) + \
            (1 - v) * jax.nn.log_sigmoid(-self.logits)

    def entropy(self):
        p = self.probs
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if logits is None:
            p = jnp.asarray(probs, jnp.float32)
            self.logits = jnp.log(p / p.sum(-1, keepdims=True))
        else:
            self.logits = jax.nn.log_softmax(
                jnp.asarray(logits, jnp.float32), axis=-1)
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.categorical(_key(key), self.logits, shape=s)

    def log_prob(self, value):
        v = jnp.asarray(value, jnp.int32)
        return jnp.take_along_axis(self.logits, v[..., None], axis=-1)[..., 0]

    def entropy(self):
        return -jnp.sum(self.probs * self.logits, axis=-1)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = jnp.asarray(alpha, jnp.float32)
        self.beta = jnp.asarray(beta, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        t = self.alpha + self.beta
        return self.alpha * self.beta / (t * t * (t + 1))

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.beta(_key(key), self.alpha, self.beta, s)

    def log_prob(self, value):
        return jax.scipy.stats.beta.logpdf(value, self.alpha, self.beta)

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        self.rate = jnp.asarray(rate, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / self.rate ** 2

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.gamma(_key(key), self.concentration, s) / self.rate

    def log_prob(self, value):
        return jax.scipy.stats.gamma.logpdf(value, self.concentration,
                                            scale=1.0 / self.rate)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a = self.concentration
        return a - jnp.log(self.rate) + gammaln(a) + (1 - a) * digamma(a)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(-1, keepdims=True)

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.dirichlet(_key(key), self.concentration, s)

    def log_prob(self, value):
        return jax.scipy.stats.dirichlet.logpdf(
            jnp.moveaxis(jnp.asarray(value), -1, 0), self.concentration)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = jnp.asarray(rate, jnp.float32)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / self.rate ** 2

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.exponential(_key(key), s) / self.rate

    def log_prob(self, value):
        return jnp.log(self.rate) - self.rate * value

    def entropy(self):
        return 1.0 - jnp.log(self.rate)

    def cdf(self, value):
        return 1 - jnp.exp(-self.rate * value)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, self.batch_shape)

    @property
    def variance(self):
        return jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape)

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return self.loc + self.scale * jax.random.laplace(_key(key), s)

    def log_prob(self, value):
        return (-jnp.abs(value - self.loc) / self.scale
                - jnp.log(2 * self.scale))

    def entropy(self):
        return jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                self.batch_shape)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return jnp.exp(self.loc + self.scale ** 2 / 2)

    @property
    def variance(self):
        return (jnp.exp(self.scale ** 2) - 1) * jnp.exp(
            2 * self.loc + self.scale ** 2)

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jnp.exp(self.loc + self.scale * jax.random.normal(_key(key), s))

    def log_prob(self, value):
        logv = jnp.log(value)
        return (-((logv - self.loc) ** 2) / (2 * self.scale ** 2)
                - logv - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return self.loc + self.scale * np.float32(np.euler_gamma)

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return self.loc + self.scale * jax.random.gumbel(_key(key), s)

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs = jnp.asarray(probs, jnp.float32)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return (1 - self.probs) / self.probs

    @property
    def variance(self):
        return (1 - self.probs) / self.probs ** 2

    def sample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        u = jax.random.uniform(_key(key), s)
        return jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs))

    def log_prob(self, value):
        return value * jnp.log1p(-self.probs) + jnp.log(self.probs)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return self.loc + self.scale * jax.random.cauchy(_key(key), s)

    def log_prob(self, value):
        return jax.scipy.stats.cauchy.logpdf(value, self.loc, self.scale)

    def entropy(self):
        return jnp.broadcast_to(jnp.log(4 * math.pi * self.scale),
                                self.batch_shape)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = jnp.asarray(df, jnp.float32)
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return self.loc + self.scale * jax.random.t(_key(key), self.df, s)

    def log_prob(self, value):
        return jax.scipy.stats.t.logpdf(value, self.df, self.loc, self.scale)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = jnp.asarray(rate, jnp.float32)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.poisson(_key(key), self.rate, s).astype(jnp.float32)

    def log_prob(self, value):
        return jax.scipy.stats.poisson.logpmf(value, self.rate)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = jnp.asarray(total_count, jnp.float32)
        self.probs = jnp.asarray(probs, jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        return jax.random.binomial(_key(key), self.total_count, self.probs,
                                   shape=s)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        n, p = self.total_count, self.probs
        v = jnp.asarray(value, jnp.float32)
        return (gammaln(n + 1) - gammaln(v + 1) - gammaln(n - v + 1)
                + v * jnp.log(p) + (n - v) * jnp.log1p(-p))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = jnp.asarray(probs, jnp.float32)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return self.total_count * self.probs

    def sample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape)
        draws = jax.random.categorical(
            _key(key), jnp.log(self.probs),
            shape=(self.total_count,) + s)
        k = self.probs.shape[-1]
        return jax.nn.one_hot(draws, k).sum(0)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = jnp.asarray(value, jnp.float32)
        return (gammaln(jnp.sum(v, -1) + 1) - jnp.sum(gammaln(v + 1), -1)
                + jnp.sum(v * jnp.log(self.probs), -1))


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = jnp.asarray(probs, jnp.float32)
        super().__init__(self.probs.shape)

    def log_prob(self, value):
        p = self.probs
        logc = jnp.where(
            jnp.abs(p - 0.5) < 1e-4, jnp.log(jnp.float32(2.0)),
            jnp.log(2 * jnp.arctanh(1 - 2 * p) / (1 - 2 * p)))
        return (logc + value * jnp.log(p) + (1 - value) * jnp.log1p(-p))


ExponentialFamily = Distribution  # API alias (reference exports it)


class Independent(Distribution):
    """Reinterprets batch dims as event dims (parity:
    distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = reinterpreted_batch_rank
        bs = base.batch_shape
        super().__init__(bs[: len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + base.event_shape)

    def sample(self, shape=(), key=None):
        return self.base.sample(shape, key=key)

    def rsample(self, shape=(), key=None):
        return self.base.rsample(shape, key=key)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return jnp.sum(lp, axis=tuple(range(-self.rank, 0)))

    def entropy(self):
        return jnp.sum(self.base.entropy(),
                       axis=tuple(range(-self.rank, 0)))


# ---------------- transforms ----------------

class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def forward(self, x):
        return jnp.tanh(x)

    def inverse(self, y):
        return jnp.arctanh(y)

    def forward_log_det_jacobian(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    def forward(self, x):
        return jnp.abs(x)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = jnp.asarray(power, jnp.float32)

    def forward(self, x):
        return jnp.power(x, self.power)

    def inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        ld = 0.0
        for t in self.transforms:
            ld = ld + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return ld


class TransformedDistribution(Distribution):
    """Parity: distribution/transformed_distribution.py."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = (transforms[0] if len(transforms) == 1
                          else ChainTransform(transforms))
        super().__init__(base.batch_shape, base.event_shape)

    def rsample(self, shape=(), key=None):
        return self.transform.forward(self.base.rsample(shape, key=key))

    def sample(self, shape=(), key=None):
        return self.transform.forward(self.base.sample(shape, key=key))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        return (self.base.log_prob(x)
                - self.transform.forward_log_det_jacobian(x))


# ---------------- KL divergence registry ----------------

_KL_REGISTRY: dict = {}


def register_kl(type_p, type_q):
    """Parity: distribution/kl.py register_kl decorator."""

    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution):
    for (tp, tq), fn in _KL_REGISTRY.items():
        if isinstance(p, tp) and isinstance(q, tq):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    return jnp.sum(p.probs * (p.logits - q.logits), axis=-1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    a = p.probs * (jnp.log(p.probs) - jnp.log(q.probs))
    b = (1 - p.probs) * (jnp.log1p(-p.probs) - jnp.log1p(-q.probs))
    return a + b


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p, q):
    return jnp.log((q.high - q.low) / (p.high - p.low))


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    r = q.rate / p.rate
    return jnp.log(p.rate) - jnp.log(q.rate) + r - 1


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    from jax.scipy.special import digamma, gammaln
    return ((p.concentration - q.concentration) * digamma(p.concentration)
            - gammaln(p.concentration) + gammaln(q.concentration)
            + q.concentration * (jnp.log(p.rate) - jnp.log(q.rate))
            + p.concentration * (q.rate / p.rate - 1))


class MultivariateNormal(Distribution):
    """Parity: distribution/multivariate_normal.py:22 — parameterized by
    exactly one of covariance_matrix / precision_matrix / scale_tril.
    Internally everything reduces to the Cholesky factor L (Sigma = L L^T):
    sampling is loc + L @ eps and log_prob uses a triangular solve, so no
    explicit inverse or determinant is ever formed."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        given = [covariance_matrix is not None, precision_matrix is not None,
                 scale_tril is not None]
        if sum(given) != 1:
            raise ValueError(
                "pass exactly one of covariance_matrix, precision_matrix, "
                "scale_tril")
        self.loc = jnp.atleast_1d(jnp.asarray(loc, jnp.float32))
        k = self.loc.shape[-1]
        if scale_tril is not None:
            self._scale_tril = jnp.asarray(scale_tril, jnp.float32)
        elif covariance_matrix is not None:
            cov = jnp.asarray(covariance_matrix, jnp.float32)
            self._scale_tril = jnp.linalg.cholesky(cov)
        else:
            prec = jnp.asarray(precision_matrix, jnp.float32)
            # Sigma = P^-1; chol(P) = Lp  =>  L = (Lp^-T) up to a rotation —
            # solve Lp^T L = I for a true lower-triangular factor of Sigma
            lp = jnp.linalg.cholesky(prec)
            eye = jnp.broadcast_to(jnp.eye(k, dtype=jnp.float32), lp.shape)
            linv = jax.scipy.linalg.solve_triangular(lp, eye, lower=True)
            # Sigma = Lp^-T Lp^-1 = (linv^T)(linv); re-cholesky for lower form
            self._scale_tril = jnp.linalg.cholesky(
                jnp.swapaxes(linv, -1, -2) @ linv)
        if self._scale_tril.shape[-1] != k:
            raise ValueError("matrix event size must match loc")
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self._scale_tril.shape[:-2])
        super().__init__(batch, (k,))

    @property
    def scale_tril(self):
        return self._scale_tril

    @property
    def covariance_matrix(self):
        return self._scale_tril @ jnp.swapaxes(self._scale_tril, -1, -2)

    @property
    def precision_matrix(self):
        k = self.loc.shape[-1]
        eye = jnp.broadcast_to(jnp.eye(k, dtype=jnp.float32),
                               self._scale_tril.shape)
        linv = jax.scipy.linalg.solve_triangular(self._scale_tril, eye,
                                                 lower=True)
        return jnp.swapaxes(linv, -1, -2) @ linv

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, self.batch_shape + self.event_shape)

    @property
    def variance(self):
        v = jnp.sum(self._scale_tril ** 2, axis=-1)
        return jnp.broadcast_to(v, self.batch_shape + self.event_shape)

    def rsample(self, shape=(), key=None):
        s = _shape(shape, self.batch_shape) + self.event_shape
        eps = jax.random.normal(_key(key), s)
        return self.loc + jnp.einsum("...ij,...j->...i", self._scale_tril, eps)

    def log_prob(self, value):
        diff = jnp.asarray(value, jnp.float32) - self.loc
        # solve L z = diff  =>  z = L^-1 diff; |z|^2 = Mahalanobis distance
        # (solve_triangular wants matching batch ranks — broadcast first)
        bshape = jnp.broadcast_shapes(diff.shape[:-1],
                                      self._scale_tril.shape[:-2])
        tril = jnp.broadcast_to(self._scale_tril,
                                bshape + self._scale_tril.shape[-2:])
        diff = jnp.broadcast_to(diff, bshape + diff.shape[-1:])
        z = jax.scipy.linalg.solve_triangular(
            tril, diff[..., None], lower=True)[..., 0]
        k = self.loc.shape[-1]
        half_logdet = jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)),
            axis=-1)
        return (-0.5 * jnp.sum(z ** 2, axis=-1) - half_logdet
                - 0.5 * k * math.log(2 * math.pi))

    def entropy(self):
        k = self.loc.shape[-1]
        half_logdet = jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)),
            axis=-1)
        return jnp.broadcast_to(
            0.5 * k * (1 + math.log(2 * math.pi)) + half_logdet,
            self.batch_shape)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    # 0.5 * (tr(Sq^-1 Sp) + m^T Sq^-1 m - k + logdet(Sq) - logdet(Sp))
    k = p.loc.shape[-1]
    lq, lp = q.scale_tril, p.scale_tril
    diff = q.loc - p.loc
    bshape = jnp.broadcast_shapes(diff.shape[:-1], lq.shape[:-2],
                                  lp.shape[:-2])
    lq = jnp.broadcast_to(lq, bshape + lq.shape[-2:])
    lp = jnp.broadcast_to(lp, bshape + lp.shape[-2:])
    diff = jnp.broadcast_to(diff, bshape + diff.shape[-1:])
    m = jax.scipy.linalg.solve_triangular(
        lq, diff[..., None], lower=True)[..., 0]
    a = jax.scipy.linalg.solve_triangular(lq, lp, lower=True)
    tr = jnp.sum(a ** 2, axis=(-2, -1))
    logdet_q = jnp.sum(jnp.log(jnp.diagonal(lq, axis1=-2, axis2=-1)), axis=-1)
    logdet_p = jnp.sum(jnp.log(jnp.diagonal(lp, axis1=-2, axis2=-1)), axis=-1)
    return 0.5 * (tr + jnp.sum(m ** 2, axis=-1) - k) + logdet_q - logdet_p
