"""Engine fleet: replicated serving with health-checked routing and
deterministic failover replay (SERVING.md "Engine fleet & failover").

``FleetRouter`` fronts N in-process data-parallel :class:`ServingEngine`
replicas (same model, same config — homogeneous) and owns the three
things a single engine cannot:

- **Admission.** One global bounded queue; when it is full ``submit``
  sheds with :class:`FleetOverloadedError` (retryable after client
  backoff). Requests the fleet could NEVER run are refused up front via
  the engines' ``admission_check`` (homogeneous replicas all reject
  identically, hence ``RequestTooLargeError.retryable = False``).
  Placement is least-loaded with best-effort prefix-cache affinity: a
  replica whose pool already holds the request's prompt prefix (the
  content-hash index, ``pool.match_prefix``) wins over an idle cold one,
  because the cached prefill is the cheaper admission.

- **Health.** Per replica: *ready* = would accept a dispatch now (not
  draining, queue below its bound, breaker not open); *live* = making
  step progress. Transient dispatch/health failures feed a
  consecutive-failure circuit breaker — at ``breaker_threshold`` the
  replica goes OPEN and is skipped for placement for a bounded
  exponential backoff (deterministic hash jitter, measured in router
  steps — no wall-clock entropy), then HALF_OPEN where a single probe
  dispatch decides: success closes the breaker, failure reopens it with
  doubled backoff. The breaker gates NEW placements only; an OPEN
  replica keeps stepping its in-flight work.

- **Failover, exactly-once.** When a replica dies (chaos kill via the
  ``fleet.replica_kill`` fault site, an unexpected exception), stalls
  (:class:`SchedulerStalledError`) or drains, the router marks it DEAD,
  dumps its flight recorder, and re-queues its in-flight requests for
  placement on a healthy replica — same rid, same prompt, same seed.
  Because the engine is bitwise deterministic (engine == ``generate()``
  parity; per-slot sampling keyed ``fold_in(PRNGKey(seed), token_idx)``,
  independent of slot placement and batch composition), the replay
  reproduces the original token stream exactly. The router tracks per
  request how many tokens the CLIENT has seen (``emitted``) versus how
  many the current replica life has produced (``produced``, reset to 0
  at each dispatch): replayed positions ``produced <= emitted`` are
  verified bitwise against the delivered stream and suppressed, the
  first fresh position is delivered — so every client sees each token
  exactly once, and the whole stream equals a single-engine run
  bit-for-bit. Replay is possible precisely because faults land at step
  boundaries: a step either completes (its events were translated) or
  raises (no events), so ``emitted`` can never include a half-delivered
  step. With a shared :class:`~.snapshot.SnapshotStore` (the engines'
  periodic captures), the replay is BOUNDED: the replacement replica
  restores the request's KV and already-generated tokens from its
  latest digest-verified snapshot and re-produces only the delta since
  capture — a missing or corrupt snapshot silently degrades to the
  full replay above (slower, never wrong; RESILIENCE.md "Serving
  recovery playbook").

The router never hangs: if every replica is DEAD (or zero placement
progress persists past ``shed_patience`` router steps) the pending
queue is shed with the classified terminal outcome
``finish_reason="shed"`` rather than spinning. Fleet-wide SIGTERM drain
composes with ``PreemptionGuard`` exactly like the single engine:
``attach_preemption_guard`` + ``stream``/``run_to_completion`` notice
the flag at a step boundary and ``drain()`` every replica, returning
structured retry-elsewhere outcomes.

Fault sites (RESILIENCE.md): ``fleet.dispatch`` (ctx path = rid),
``fleet.replica_kill`` and ``fleet.health`` (ctx path = replica index,
so ``match=r"^1$"`` chaos-kills exactly replica 1); the router also
sets each pool's ``fault_path`` to the replica index so a
``serving.alloc`` storm can be pinned to one replica.

Homogeneous replicas may share ONE :class:`~.tiering.HostTier`
(``ServingEngine(..., host_tier=tier)`` with the same instance): tier
keys are chained content hashes namespaced per KV dtype, so a page
spilled by replica A restores bit-exactly on replica B — after a
failover the replacement replica warm-starts from the dead replica's
spilled prefixes instead of recomputing them (chaos-tested in
``tests/test_serving_tiering.py::TestTieredChaos``).
"""

from __future__ import annotations

import bisect
import hashlib
from dataclasses import dataclass, field

from ..distributed import fault as _fault
from ..observability.trace import NULL_TRACER
from .errors import (EngineDrainingError, FleetOverloadedError,
                     RequestTooLargeError, SchedulerStalledError,
                     ServingError)
from .metrics import FleetMetrics, ServingMetrics
from .scheduler import SamplingParams

__all__ = ["FleetRouter", "FleetRequest",
           "CLOSED", "OPEN", "HALF_OPEN", "DEAD"]

# replica/breaker states
CLOSED = "closed"          # healthy, accepts placements
OPEN = "open"              # breaker open: no placements until backoff ends
HALF_OPEN = "half_open"    # probing: one placement decides close/reopen
DEAD = "dead"              # ejected (killed/stalled) — terminal

_SHED_PATIENCE = 50        # zero-progress router steps before shedding


@dataclass
class FleetRequest:
    """Router-side request record — the client's view of the stream.

    ``tokens`` is the client-visible stream (exactly-once);
    ``emitted`` == len(tokens) survives failover while ``produced``
    counts the CURRENT replica life and resets to 0 at every dispatch,
    which is what makes replay dedup a pair of integer compares."""
    rid: str
    prompt: list[int]
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: int | None
    deadline_s: float | None
    max_queue_wait_s: float | None
    submit_seq: int
    tenant: int = 0            # fair-scheduling / quota scope on replicas
    priority: int = 0          # larger = more important (brownout shed order)
    tokens: list[int] = field(default_factory=list)
    emitted: int = 0           # tokens the client has seen (== len(tokens))
    produced: int = 0          # tokens produced by the current replica life
    finished: bool = False
    finish_reason: str | None = None
    replica: int | None = None  # current placement (None = router queue)
    replays: int = 0            # failover re-dispatches


@dataclass
class _Replica:
    idx: int
    engine: object
    state: str = CLOSED
    consecutive_failures: int = 0
    opens: int = 0              # times the breaker opened (backoff exponent)
    backoff_until: int = 0      # router step when HALF_OPEN probing begins
    last_progress_step: int = 0
    dead_reason: str | None = None
    dump_path: str | None = None


class FleetRouter:
    """Front-end over N homogeneous ``ServingEngine`` replicas.

    The public surface mirrors the single engine on purpose —
    ``submit`` (its ``add_request``), ``step``, ``stream``,
    ``run_to_completion``, ``drain``, ``attach_preemption_guard``,
    ``request``, ``stats`` — so a caller written against one engine
    upgrades to a fleet by swapping the constructor. The router keeps
    its OWN ``ServingMetrics`` fed only by client-delivered events, so
    its TTFT/ITL/goodput are the honest client-visible numbers across
    failovers (a replayed token that was suppressed never counts
    twice); per-replica engine metrics stay on the engines.
    """

    def __init__(self, engines, max_queue_depth: int | None = None,
                 breaker_threshold: int = 3,
                 breaker_backoff_steps: int = 2,
                 breaker_backoff_max: int = 16,
                 shed_patience: int = _SHED_PATIENCE,
                 clock=None, tracer=None, snapshot_store=None):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        self._replicas = [_Replica(i, e) for i, e in enumerate(engines)]
        for rep in self._replicas:
            pool = getattr(rep.engine, "pool", None)
            if pool is not None:
                # pin serving.alloc fault draws to this replica's index
                pool.fault_path = str(rep.idx)
        self.max_queue_depth = max_queue_depth
        self.breaker_threshold = breaker_threshold
        self.breaker_backoff_steps = breaker_backoff_steps
        self.breaker_backoff_max = breaker_backoff_max
        self.shed_patience = shed_patience
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = ServingMetrics(clock)     # client-visible stream
        self.fleet_metrics = FleetMetrics()
        self._records: dict[str, FleetRequest] = {}
        self._pending: list[FleetRequest] = []   # router queue, submit order
        # bounded-replay failover (serving/snapshot.py): the shared
        # store the replicas capture into — it models the off-replica
        # durable medium, so a replica's death never takes its
        # requests' snapshots with it. Auto-discovered from the engines
        # when not passed explicitly; None -> every failover is a full
        # replay from token 0 (the pre-snapshot behaviour).
        self._snapshot_store = snapshot_store
        if self._snapshot_store is None:
            for e in engines:
                store = getattr(e, "snapshot_store", None)
                if store is not None:
                    self._snapshot_store = store
                    break
        # rid -> ejection time: open recovery windows, closed by the
        # first FRESH post-recovery token (time-to-first-recovered-token)
        self._recovering: dict[str, float] = {}
        self._submit_seq = 0
        self._steps = 0
        # router-step duration EMA (metrics clock): the timing input to
        # the deterministic retry_after_s hint on fleet-level sheds
        self._step_dt_ema: float | None = None
        self._idle_steps = 0
        self._draining = False
        self._guard = None
        self.last_drain_events: list[dict] = []

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               sampling: SamplingParams | None = None,
               eos_token_id: int | None = None,
               rid: str | None = None,
               deadline_s: float | None = None,
               max_queue_wait_s: float | None = None,
               tenant: int = 0, priority: int = 0) -> str:
        """Fleet admission. A full global queue sheds with
        :class:`FleetOverloadedError` (carrying ``retry_after_s``, the
        router's drain-rate estimate — RESILIENCE.md "Overload
        playbook"); a request no replica could EVER run raises
        :class:`RequestTooLargeError` here, before it occupies queue
        space anywhere (homogeneous fleet — replica 0's
        ``admission_check`` speaks for all). ``tenant``/``priority``
        ride the record to every placement (fair scheduling, quotas and
        brownout shed order on the replicas — SERVING.md "Overload
        control & tenant fairness"). Placement happens at the next
        ``step()``, not here: dispatch failures are the router's to
        retry, never the client's."""
        if self._draining:
            raise EngineDrainingError(
                "fleet is draining (preempted or shut down); "
                "retry against another fleet")
        if (self.max_queue_depth is not None
                and len(self._pending) >= self.max_queue_depth):
            retry = self._retry_after_s()
            self.fleet_metrics.bump("shed")
            self.metrics.on_reject("queue_full")
            self.metrics.on_shed(int(tenant), int(priority))
            raise FleetOverloadedError(
                f"fleet queue at max_queue_depth={self.max_queue_depth}; "
                f"request shed (every replica saturated — retry after "
                f"~{retry:.3f}s with backoff, or scale out)",
                retry_after_s=retry)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        check = getattr(self._replicas[0].engine, "admission_check", None)
        if check is not None:
            try:
                check(len(prompt), max_new_tokens)
            except RequestTooLargeError:
                self.metrics.on_reject("too_large")
                raise
        rid = rid if rid is not None else f"fleet-req-{self._submit_seq}"
        if rid in self._records:
            raise ValueError(f"duplicate request id {rid!r}")
        rec = FleetRequest(rid=rid, prompt=prompt,
                           max_new_tokens=max_new_tokens,
                           sampling=sampling or SamplingParams(),
                           eos_token_id=eos_token_id,
                           deadline_s=deadline_s,
                           max_queue_wait_s=max_queue_wait_s,
                           submit_seq=self._submit_seq,
                           tenant=int(tenant), priority=int(priority))
        self._submit_seq += 1
        self._records[rid] = rec
        self._pending.append(rec)
        self.metrics.on_arrival(rid, tenant=int(tenant),
                                priority=int(priority))
        self.tracer.instant("submit", track="fleet", rid=rid,
                            queue=len(self._pending))
        return rid

    def _retry_after_s(self) -> float:
        """Deterministic fleet drain-rate estimate behind the
        ``retry_after_s`` hint on FleetOverloadedError and router shed
        events: service tokens held by the router queue over the live
        replicas' combined per-step token capacity, scaled by the
        router-step-duration EMA (metrics clock). 0.0 before the first
        timed step — honest "no data yet", never a made-up constant."""
        if self._step_dt_ema is None or self._step_dt_ema <= 0.0:
            return 0.0
        tokens = sum(len(r.prompt) + r.max_new_tokens
                     for r in self._pending)
        cap = 0
        for rep in self._replicas:
            if rep.state == DEAD:
                continue
            per_step = getattr(rep.engine, "_token_capacity_per_step",
                               None)
            cap += int(per_step()) if per_step is not None else 1
        return tokens / max(cap, 1) * self._step_dt_ema

    # ------------------------------------------------------------------
    # stepping
    # ------------------------------------------------------------------

    def step(self) -> list[dict]:
        """One router iteration: chaos/health sweep, placement of the
        router queue, one engine step per live replica (ejecting and
        failing over any that die or stall), and exactly-once
        translation of their events into client events. Bounded work —
        a replica that cannot accept work this step is retried next
        step, never spun on."""
        t_step0 = self.metrics.now()
        events: list[dict] = []
        self._kill_sweep()
        self._health_sweep()
        self._dispatch(events)
        progressed = bool(events)
        for rep in list(self._replicas):
            if rep.state == DEAD or not rep.engine.scheduler.has_work():
                continue
            try:
                replica_events = rep.engine.step()
            except SchedulerStalledError as e:
                self._eject(rep, "stalled", snapshot=e.snapshot)
                continue
            except ServingError as e:
                self._eject(rep, f"error:{type(e).__name__}")
                continue
            except _fault.FaultInjected:
                self._eject(rep, "killed")
                continue
            if replica_events:
                rep.last_progress_step = self._steps
                progressed = True
            self._translate(rep, replica_events, events)
        self._steps += 1
        if progressed or not self._pending:
            self._idle_steps = 0
        else:
            self._idle_steps += 1
        alive = [r for r in self._replicas if r.state != DEAD]
        if self._pending and (not alive
                              or self._idle_steps >= self.shed_patience):
            # no-hang guarantee: nothing can place these — classify and
            # finish them instead of spinning (terminal, retryable at
            # the client since nothing was computed)
            for rec in list(self._pending):
                self._finish_record(rec, "shed", events)
            self._pending.clear()
        dt = self.metrics.now() - t_step0
        if dt > 0.0:
            self._step_dt_ema = (dt if self._step_dt_ema is None
                                 else 0.8 * self._step_dt_ema + 0.2 * dt)
        return events

    def has_work(self) -> bool:
        if self._pending:
            return True
        return any(rep.state != DEAD and rep.engine.scheduler.has_work()
                   for rep in self._replicas)

    def stream(self):
        """Drive the fleet to completion, yielding client events —
        ``{"rid", "token", "finished", "finish_reason", "replica"}`` —
        exactly once each, in production order. On a tripped preemption
        guard the fleet drains and the terminal events are yielded."""
        while self.has_work():
            if self._preemption_pending():
                self.drain()
                yield from self.last_drain_events
                return
            yield from self.step()

    def run_to_completion(self, max_steps: int | None = None) -> dict:
        """Drain the fleet; {rid: client-visible token list}. Raises
        after ``max_steps`` router steps — the chaos suites' hang
        tripwire."""
        steps = 0
        while self.has_work():
            if self._preemption_pending():
                self.drain()
                break
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {steps} router steps")
        return {rid: list(r.tokens) for rid, r in self._records.items()}

    # ------------------------------------------------------------------
    # drain / preemption
    # ------------------------------------------------------------------

    def drain(self, timeout_s: float | None = None) -> dict:
        """Fleet-wide graceful shutdown: shed the router queue as
        retriable ``preempted`` outcomes (nothing was computed for
        them), then drain every live replica — running requests decode
        to their own finish within ``timeout_s`` (per replica, on its
        metrics clock) and their events flow through the exactly-once
        translation like any other step. Returns
        {rid: {finish_reason, tokens, retriable}} over ALL fleet
        requests; terminal events land in ``last_drain_events``."""
        events: list[dict] = []
        self._draining = True
        for rec in list(self._pending):
            self._finish_record(rec, "preempted", events)
        self._pending.clear()
        for rep in self._replicas:
            if rep.state == DEAD or not rep.engine.scheduler.has_work():
                continue
            try:
                rep.engine.drain(timeout_s=timeout_s)
                self._translate(rep, rep.engine.last_drain_events, events)
            except (ServingError, _fault.FaultInjected):
                self._eject(rep, "died_in_drain")
        # anything still unfinished (its replica died mid-drain and
        # there is nowhere left to replay) is preempted: retryable,
        # nothing the client saw is lost
        for rec in self._records.values():
            if not rec.finished:
                self._finish_record(rec, "preempted", events)
        self.last_drain_events = events
        self.tracer.instant("fleet_drain", track="fleet",
                            requests=len(self._records))
        return {rid: {"finish_reason": rec.finish_reason,
                      "tokens": list(rec.tokens),
                      "retriable": rec.finish_reason in ("preempted",
                                                         "shed")}
                for rid, rec in self._records.items()}

    def attach_preemption_guard(self, guard=None):
        """Fleet-wide SIGTERM handling: one guard covers every replica —
        ``stream``/``run_to_completion`` notice ``guard.preempted`` at a
        router-step boundary and ``drain()`` the whole fleet (structured
        retry-elsewhere outcomes, same contract as the single engine)."""
        if guard is None:
            from ..distributed import PreemptionGuard
            guard = PreemptionGuard()
        self._guard = guard
        return guard

    def _preemption_pending(self) -> bool:
        return (self._guard is not None and self._guard.preempted
                and not self._draining)

    # ------------------------------------------------------------------
    # health / breaker
    # ------------------------------------------------------------------

    def health(self, idx: int) -> dict:
        """One replica's health view: *ready* (would accept a dispatch
        now — queue/pool pressure + breaker), *live* (step progress;
        vacuously true while it has nothing to do), and the breaker
        bookkeeping an operator alerts on."""
        rep = self._replicas[idx]
        eng = rep.engine
        sched = eng.scheduler
        qd = sched.queue_depth
        pool = getattr(eng, "pool", None)
        has_work = sched.has_work()
        return {
            "replica": idx,
            "state": rep.state,
            "ready": self._ready(rep),
            "live": (rep.state != DEAD
                     and (not has_work
                          or self._steps - rep.last_progress_step
                          <= self.shed_patience)),
            "queue_depth": qd,
            "running": len(sched.running),
            "pool_utilization": (pool.utilization()
                                 if pool is not None else 0.0),
            # a replica is a TP *group*: tp devices serving one engine.
            # One device failing takes the whole group — the breaker /
            # failover-replay path below is the same either way
            # (RESILIENCE.md), this gauge just sizes the blast radius.
            "tp_degree": getattr(eng, "tp", 1),
            # overload-control gauge: which brownout rung this replica
            # is on (0 = normal service; engines without the ladder
            # always read 0)
            "brownout_level": getattr(eng, "brownout_level", 0),
            "consecutive_failures": rep.consecutive_failures,
            "breaker_opens": rep.opens,
            "backoff_remaining": max(0, rep.backoff_until - self._steps),
            "dead_reason": rep.dead_reason,
            "flight_recorder": rep.dump_path,
        }

    def _ready(self, rep: _Replica) -> bool:
        if rep.state == DEAD or rep.state == OPEN:
            return False
        eng = rep.engine
        if getattr(eng, "_draining", False):
            return False
        mqd = getattr(eng.scheduler, "max_queue_depth", None)
        if mqd is not None and eng.scheduler.queue_depth >= mqd:
            return False
        return True

    def _health_sweep(self) -> None:
        """Advance breaker timers + fire the ``fleet.health`` site per
        live replica (an injected health failure counts as a transient
        breaker failure, exactly like a failed dispatch)."""
        for rep in self._replicas:
            if rep.state == DEAD:
                continue
            if rep.state == OPEN and self._steps >= rep.backoff_until:
                rep.state = HALF_OPEN
                self.fleet_metrics.bump("probes")
                self.tracer.instant("breaker_half_open", track="fleet",
                                    replica=rep.idx)
            try:
                _fault.trip("fleet.health", step=self._steps,
                            path=str(rep.idx))
            except _fault.FaultInjected:
                self._breaker_failure(rep)

    def _breaker_failure(self, rep: _Replica) -> None:
        rep.consecutive_failures += 1
        if rep.state == HALF_OPEN or (
                rep.state == CLOSED
                and rep.consecutive_failures >= self.breaker_threshold):
            rep.opens += 1
            rep.state = OPEN
            backoff = min(
                self.breaker_backoff_steps * (2 ** (rep.opens - 1)),
                self.breaker_backoff_max)
            rep.backoff_until = self._steps + backoff + self._jitter(
                rep.idx, rep.opens, backoff)
            self.fleet_metrics.bump("breaker_opens")
            self.tracer.instant("breaker_open", track="fleet",
                                replica=rep.idx, opens=rep.opens,
                                until=rep.backoff_until)

    def _breaker_success(self, rep: _Replica) -> None:
        rep.consecutive_failures = 0
        if rep.state == HALF_OPEN:
            rep.state = CLOSED
            self.tracer.instant("breaker_close", track="fleet",
                                replica=rep.idx)

    @staticmethod
    def _jitter(idx: int, opens: int, backoff: int) -> int:
        """Deterministic jitter in [0, backoff): a hash draw, never
        wall-clock entropy, so chaos runs replay bit-identically."""
        if backoff <= 1:
            return 0
        h = hashlib.sha256(f"fleet-jitter:{idx}:{opens}".encode()).digest()
        return int.from_bytes(h[:4], "big") % backoff

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------

    def _dispatch(self, events: list[dict]) -> None:
        """Place router-queued records onto ready replicas, FCFS by
        submit order. Best-effort prefix-cache affinity first (largest
        ``match_prefix`` hit), then least-loaded; every failure is a
        breaker data point and the record simply stays queued for the
        next step — bounded work, no spinning."""
        if not self._pending:
            return
        placed: list[FleetRequest] = []
        for rec in self._pending:
            candidates = [rep for rep in self._replicas
                          if self._ready(rep)]
            if not candidates:
                break  # nothing can take the head now — FCFS, try later
            ordered = sorted(
                candidates,
                key=lambda rep: (-self._affinity(rep, rec),
                                 self._load(rep), rep.idx))
            ok = False
            for rep in ordered:
                if self._try_place(rec, rep, events):
                    ok = True
                    break
                if rec.finished:   # non-retryable dispatch classification
                    ok = True
                    break
            if ok:
                placed.append(rec)
        for rec in placed:
            self._pending.remove(rec)

    @staticmethod
    def _load(rep: _Replica) -> int:
        sched = rep.engine.scheduler
        return sched.queue_depth + len(sched.running)

    @staticmethod
    def _affinity(rep: _Replica, rec: FleetRequest) -> int:
        """Cached-prefix tokens this replica's pool already holds for
        the prompt — pure lookup against the content-hash index."""
        pool = getattr(rep.engine, "pool", None)
        if pool is None or not getattr(pool, "cache_enabled", False):
            return 0
        try:
            return int(pool.match_prefix(rec.prompt).cached_tokens)
        except Exception:  # noqa: BLE001 — affinity is best-effort only
            return 0

    def _usable_snapshot(self, rec: FleetRequest):
        """The record's latest VERIFIED snapshot, iff seeding from it
        is provably safe: its token prefix must already be in the
        client-delivered stream (len <= emitted, bitwise equal) —
        seeded tokens are never re-emitted by the engine, so a token
        beyond the delivered stream would silently vanish. Anything
        else (missing, digest-corrupt, ahead of the client) returns
        None and the failover degrades to full replay from token 0 —
        slower, never wrong."""
        store = self._snapshot_store
        if store is None:
            return None
        snap = store.get(rec.rid)   # digest-re-verified; corrupt -> None
        if snap is None:
            return None
        n = len(snap.tokens)
        if n > rec.emitted or list(snap.tokens) != rec.tokens[:n]:
            return None
        return snap

    def _try_place(self, rec: FleetRequest, rep: _Replica,
                   events: list[dict]) -> bool:
        # bounded-replay failover: a replayed record with a usable
        # snapshot restores from it (KV injected, tokens seeded) and
        # replays only the delta since capture; the seeded tokens flow
        # through the SAME emitted-vs-produced dedup via the produced
        # counter, so client streams stay exactly-once and bitwise
        snap = self._usable_snapshot(rec) if rec.replays else None
        restore = getattr(rep.engine, "restore_request", None)
        if restore is None:
            snap = None
        # tenant/priority ride every placement (fair scheduling, quotas
        # and brownout shed order on the replica — restore included, so
        # SURVIVOR quotas govern failover replay); forwarded only when
        # set, keeping duck-typed engines without tenancy working
        tp_kw = ({"tenant": rec.tenant, "priority": rec.priority}
                 if (rec.tenant, rec.priority) != (0, 0) else {})
        try:
            _fault.trip("fleet.dispatch", step=self._steps, path=rec.rid)
            if snap is not None:
                restore(snap, **tp_kw)
            else:
                rep.engine.add_request(
                    rec.prompt, rec.max_new_tokens, sampling=rec.sampling,
                    eos_token_id=rec.eos_token_id, rid=rec.rid,
                    deadline_s=rec.deadline_s,
                    max_queue_wait_s=rec.max_queue_wait_s, **tp_kw)
        except RequestTooLargeError:
            # cannot happen after submit-time admission_check on a
            # homogeneous fleet, but a duck-typed engine may disagree:
            # classify, never retry (retryable=False)
            self._finish_record(rec, "rejected_too_large", events)
            return False
        except (ServingError, _fault.FaultInjected):
            # retryable=True territory (queue full / draining / injected
            # dispatch fault): breaker data point, record stays queued
            self._breaker_failure(rep)
            return False
        self._breaker_success(rep)
        rec.replica = rep.idx
        # the replica's first emission is token index len(snap.tokens):
        # seeding produced keeps the dedup's position arithmetic exact
        rec.produced = len(snap.tokens) if snap is not None else 0
        if rec.replays:
            fm = self.fleet_metrics
            # THE bounded-vs-full A/B number: tokens this failover still
            # re-produces (full replay pays the whole emitted count)
            fm.bump("recovery_replayed_tokens", rec.emitted - rec.produced)
            if snap is not None:
                fm.bump("snapshot_restores")
                fm.bump("recovery_restored_tokens", rec.produced)
            elif self._snapshot_store is not None:
                fm.bump("snapshot_fallbacks")
        self.metrics.on_admit(rec.rid)
        self.fleet_metrics.bump("dispatched")
        if rec.replays:
            self.fleet_metrics.bump("replayed_requests")
        self.tracer.instant("dispatch", track="fleet", rid=rec.rid,
                            replica=rep.idx, replay=rec.replays,
                            restored=rec.produced)
        return True

    # ------------------------------------------------------------------
    # failure handling
    # ------------------------------------------------------------------

    def _kill_sweep(self) -> None:
        """The ``fleet.replica_kill`` chaos site: an armed ``raise``
        matching a replica index kills that replica at this step
        boundary (between engine steps — never mid-step, which is what
        keeps replay exactly-once)."""
        if _fault.active_plan() is None:
            return
        for rep in self._replicas:
            if rep.state == DEAD:
                continue
            try:
                _fault.trip("fleet.replica_kill", step=self._steps,
                            path=str(rep.idx))
            except _fault.FaultInjected:
                self._eject(rep, "killed")

    def kill_replica(self, idx: int, reason: str = "killed") -> None:
        """Operational/chaos API: eject a replica NOW and fail its
        in-flight requests over (equivalent to a replica_kill fault)."""
        rep = self._replicas[idx]
        if rep.state != DEAD:
            self._eject(rep, reason)

    def _eject(self, rep: _Replica, reason: str,
               snapshot: dict | None = None) -> None:
        """Replica death: flight-recorder dump, DEAD state, and failover
        — every live request it held goes back to the router queue (in
        submit order) for deterministic replay on a healthy replica."""
        rep.state = DEAD
        rep.dead_reason = reason
        self.fleet_metrics.bump("ejections")
        recorder = getattr(rep.engine, "flight_recorder", None)
        if recorder is not None:
            try:
                rep.dump_path = recorder.dump(
                    f"fleet_eject_{reason}",
                    snapshot={"replica": rep.idx, "reason": reason,
                              **(snapshot or {})})
            except OSError:
                rep.dump_path = None
        self.tracer.instant("replica_eject", track="fleet",
                            replica=rep.idx, reason=reason)
        live = getattr(rep.engine.scheduler, "live_requests", None)
        if live is not None:
            survivors = live()
        else:
            survivors = (list(rep.engine.scheduler.waiting)
                         + list(rep.engine.scheduler.running.values()))
        for req in survivors:
            rec = self._records.get(req.rid)
            if rec is None or rec.finished:
                continue
            rec.replica = None
            rec.produced = 0
            rec.replays += 1
            # open the recovery window (closed by the first fresh
            # token); a second ejection mid-recovery keeps the original
            # start so TTFRT measures the whole client-visible gap
            self._recovering.setdefault(rec.rid, self.metrics.now())
            self.fleet_metrics.bump("failovers")
            keys = [r.submit_seq for r in self._pending]
            self._pending.insert(
                bisect.bisect_left(keys, rec.submit_seq), rec)
            self.tracer.instant("failover", track="fleet", rid=rec.rid,
                                emitted=rec.emitted, replica=rep.idx)

    # ------------------------------------------------------------------
    # exactly-once translation
    # ------------------------------------------------------------------

    def _translate(self, rep: _Replica, replica_events: list[dict],
                   out: list[dict]) -> None:
        """Engine events -> client events, deduping replayed positions.

        A token at position ``produced <= emitted`` is a replay of one
        the client already has: it is verified bitwise against the
        delivered stream (the determinism contract — a mismatch is a
        hard error, not a silent corruption) and suppressed. The first
        fresh position is delivered and ``emitted`` advances. Terminal
        classification events (token None) always deliver — they can
        never duplicate, because a finished record leaves the in-flight
        set and is never replayed."""
        for ev in replica_events:
            rec = self._records.get(ev["rid"])
            if rec is None or rec.finished:
                continue  # not ours / already terminal (late drain echo)
            token = ev.get("token")
            if token is not None:
                rec.produced += 1
                if rec.produced <= rec.emitted:
                    expected = rec.tokens[rec.produced - 1]
                    if token != expected:
                        raise RuntimeError(
                            f"replay divergence for {rec.rid!r} at "
                            f"position {rec.produced}: replica "
                            f"{rep.idx} produced {token}, client was "
                            f"delivered {expected} — the deterministic-"
                            f"replay contract is broken")
                    self.fleet_metrics.bump("replayed_tokens")
                    if not ev.get("finished"):
                        continue   # pure replay: suppress
                    # a finish can only ride the LAST token; if that
                    # position was already emitted the original replica
                    # died after computing it but before the router saw
                    # it — impossible by construction (step boundaries),
                    # guarded anyway:
                    token = None
                else:
                    rec.emitted += 1
                    rec.tokens.append(token)
                    self.metrics.on_token(rec.rid)
                    t0 = self._recovering.pop(rec.rid, None)
                    if t0 is not None:
                        # first FRESH token after a failover: close the
                        # time-to-first-recovered-token window
                        self.fleet_metrics.observe_recovery(
                            self.metrics.now() - t0)
            if ev.get("finished"):
                reason = ev.get("finish_reason")
                rec.finished = True
                rec.finish_reason = reason
                self._recovering.pop(rec.rid, None)
                self.metrics.on_finish(rec.rid, reason)
                if reason not in ("stop", "length"):
                    self.metrics.on_outcome(reason)
                self.tracer.instant("finish", track="fleet", rid=rec.rid,
                                    reason=reason or "",
                                    replica=rep.idx)
            if token is not None or ev.get("finished"):
                out.append({"rid": rec.rid, "token": token,
                            "finished": bool(ev.get("finished")),
                            "finish_reason": ev.get("finish_reason"),
                            "replica": rep.idx})

    def _finish_record(self, rec: FleetRequest, reason: str,
                       events: list[dict]) -> None:
        """Router-side terminal classification (shed / preempted /
        rejected): the client gets a typed outcome, never silence."""
        rec.finished = True
        rec.finish_reason = reason
        rec.replica = None
        ev = {"rid": rec.rid, "token": None, "finished": True,
              "finish_reason": reason, "replica": None}
        if reason == "shed":
            self.fleet_metrics.bump("shed")
            self.metrics.on_shed(rec.tenant, rec.priority)
            # clients implement backoff off the event itself
            # (RESILIENCE.md "Overload playbook")
            ev["retry_after_s"] = self._retry_after_s()
        self.metrics.on_finish(rec.rid, reason)
        self.metrics.on_outcome(reason)
        events.append(ev)
        self.tracer.instant("finish", track="fleet", rid=rec.rid,
                            reason=reason)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def request(self, rid: str) -> FleetRequest:
        return self._records[rid]

    def replicas_live(self) -> int:
        return sum(1 for rep in self._replicas if rep.state != DEAD)

    def stats(self) -> dict:
        """Fleet-level stats: router counters + per-replica health (the
        shape ``observability.render_fleet_prometheus`` exports)."""
        return {
            "steps": self._steps,
            "replicas": len(self._replicas),
            "replicas_live": self.replicas_live(),
            "replicas_ejected": sum(1 for r in self._replicas
                                    if r.state == DEAD),
            "queue_depth": len(self._pending),
            "requests": len(self._records),
            "draining": self._draining,
            "fleet": self.fleet_metrics.summary(),
            "replica_health": [self.health(i)
                               for i in range(len(self._replicas))],
        }

    @property
    def engines(self):
        return [rep.engine for rep in self._replicas]

    @property
    def snapshot_store(self):
        """The shared bounded-replay snapshot store (None = every
        failover is a full replay)."""
        return self._snapshot_store
