"""Engine fleet: replicated serving with health-checked routing and
deterministic failover replay (SERVING.md "Engine fleet & failover",
"Fleet transport & membership").

``FleetRouter`` fronts N data-parallel :class:`ServingEngine` replicas
(same model, same config — homogeneous) and owns the three things a
single engine cannot:

- **Admission.** One global bounded queue; when it is full ``submit``
  sheds with :class:`FleetOverloadedError` (retryable after client
  backoff). Requests the fleet could NEVER run are refused up front via
  the engines' ``admission_check`` (homogeneous replicas all reject
  identically, hence ``RequestTooLargeError.retryable = False``).
  Placement is least-loaded with best-effort prefix-cache affinity: a
  replica whose pool already holds the request's prompt prefix (the
  content-hash index, ``pool.match_prefix``) wins over an idle cold one,
  because the cached prefill is the cheaper admission.

- **Health.** Lease-based membership over the transport: the router
  heartbeats every replica (seq-numbered, deterministically phased via
  the shared :func:`~.transport.deterministic_jitter`) and reads health
  from the gauges piggybacked on every reply — never by calling the
  engine directly, because over a real wire there is no engine to call.
  A heartbeat unacked past half the lease marks the replica SUSPECT
  (one circuit-breaker failure per missed seq — the same breaker that
  absorbs dispatch failures); total silence past ``lease_steps`` router
  steps expires the lease and ejects the replica (``lease_expired``,
  counted). The breaker itself is unchanged: at ``breaker_threshold``
  consecutive failures the replica goes OPEN and is skipped for
  placement for a bounded exponential backoff (deterministic hash
  jitter, measured in router steps — no wall-clock entropy), then
  HALF_OPEN where a single probe dispatch decides. The breaker gates
  NEW placements only; an OPEN replica keeps stepping its in-flight
  work.

- **Failover, exactly-once — now over a lossy wire.** ALL
  router<->replica traffic crosses a :class:`~.transport.Transport`
  (:class:`~.transport.LoopbackTransport` by default — bitwise
  identical to the old in-process fleet; a seeded
  :class:`~.transport.ChaosTransport` for hostile-network tests).
  Replies stream back on a per-replica ordered, acked, retransmitted
  channel: the router applies result batches in seq order, buffers the
  future, suppresses duplicates (``duplicates_suppressed``), and the
  replica-side :class:`~.transport.EngineServer` resends whatever the
  router has not acked — at-least-once delivery + receiver dedup =
  exactly-once application. Each replica life has a monotonically
  increasing **epoch**, bumped at ejection: a zombie replica returning
  from a partition keeps sending with its old epoch, and every such
  message is counted (``stale_epoch_discarded``) and dropped — it can
  neither ack stale work nor double-emit. When a replica dies (chaos
  kill via the ``fleet.replica_kill`` fault site, a typed ERROR from
  its server, a lapsed lease), the router marks it DEAD, dumps its
  flight recorder, and re-queues its in-flight requests from its OWN
  records (the dead replica cannot be asked) for placement on a healthy
  replica — same rid, same prompt, same seed. Because the engine is
  bitwise deterministic (per-slot sampling keyed
  ``fold_in(PRNGKey(seed), token_idx)``), the replay reproduces the
  original stream exactly; replayed positions ``produced <= emitted``
  are verified bitwise and suppressed, so every client sees each token
  exactly once even across drops, duplicates, reordering and healed
  partitions. With a shared :class:`~.snapshot.SnapshotStore` the
  replay is BOUNDED (snapshot-seeded, delta-only); replicas with
  private stores are harvested over the wire via SNAPSHOT_FETCH, each
  snapshot re-verified through its own digests at receive — a corrupt
  one is stripped and the failover degrades to full replay.

The router never hangs: if every replica is DEAD (or zero placement
progress persists past ``shed_patience`` router steps) the pending
queue is shed with the classified terminal outcome
``finish_reason="shed"`` rather than spinning. Fleet-wide SIGTERM drain
composes with ``PreemptionGuard`` exactly like the single engine.

With ``placement="disagg"`` the fleet splits into PREFILL-specialist
and DECODE-specialist replicas (SERVING.md "Disaggregated serving").
Fresh requests place only on prefill-role replicas with
``prefill_only=True`` — the engine runs the prompt through mixed-step
chunks at full prefill budget and, instead of emitting a first token,
exports the finished KV (HostTier payload format, per-page blake2b
digests) and finishes the request locally with
``finish_reason="handoff"``. The router treats that finish as a phase
transition, not a terminal: the record re-enters the router queue at
its ORIGINAL submit order, the replica's ``KV_OFFER`` (a seq-numbered
stream message, so at-least-once + dedup + epoch fencing are free)
parks the sealed snapshot in the router's offer table, and the next
dispatch sends a ``KV_PULL`` to a decode-role replica, which lands the
pages via ``inject_prefix`` and serves the ENTIRE decode phase — the
first token included — from its ``[max_slots]`` decode program after
one forced suffix row through the mixed program. Because the decode
side recomputes exactly the row the colocated engine would have
sampled from (same seed, same ``fold_in(PRNGKey(seed), 0)`` key),
streams are bitwise identical to a colocated run and the existing
emitted-vs-produced dedup keeps them exactly-once. A landed pull is
acknowledged to the prefill side with ``KV_COMMIT`` (frees its held
copy); every failure degrades DOWN the recompute ladder, never wrong:
offer dropped/corrupt (the wire's digest gate strips a damaged
payload) or prefill source dead before offering or offer waited past
``handoff_timeout_steps`` -> the record falls back to a plain
colocated recompute on any replica. Role re-rolling is elastic: every
``reroll_interval`` steps a sustained pressure imbalance (router queue
+ prefill load vs decode load + brownout rungs, ``reroll_dwell``
consecutive readings) flips one IDLE replica to the starved role — an
extinct role is restored immediately, and a fleet whose prefill side
died entirely simply colocates until it recovers.

Fault sites (RESILIENCE.md): ``fleet.dispatch`` (ctx path = rid),
``fleet.replica_kill`` and ``fleet.health`` (ctx path = replica index),
``fleet.handoff`` (ctx path = rid; actions drop/delay/corrupt the
KV-offer payload in flight), plus the per-message
``fleet.transport.send`` / ``fleet.transport.recv`` sites inside the
transport itself (ctx path = ``"<KIND>:<rid>"``, actions
drop/dup/delay/corrupt); the router also sets each pool's
``fault_path`` to the replica index so a ``serving.alloc`` storm can be
pinned to one replica.

Homogeneous replicas may share ONE :class:`~.tiering.HostTier`: tier
keys are chained content hashes namespaced per KV dtype, so a page
spilled by replica A restores bit-exactly on replica B — after a
failover the replacement replica warm-starts from the dead replica's
spilled prefixes instead of recomputing them (chaos-tested in
``tests/test_serving_tiering.py::TestTieredChaos``).
"""

from __future__ import annotations

import bisect
from dataclasses import asdict, dataclass, field

from ..distributed import fault as _fault
from ..observability.trace import NULL_TRACER
from .errors import (EngineDrainingError, FleetOverloadedError,
                     RequestTooLargeError)
from .metrics import FleetMetrics, ServingMetrics, percentile
from .scheduler import SamplingParams
from .transport import (EngineServer, LoopbackTransport, Message,
                        deterministic_jitter)

__all__ = ["FleetRouter", "FleetRequest",
           "CLOSED", "OPEN", "HALF_OPEN", "DEAD"]

# replica/breaker states
CLOSED = "closed"          # healthy, accepts placements
OPEN = "open"              # breaker open: no placements until backoff ends
HALF_OPEN = "half_open"    # probing: one placement decides close/reopen
DEAD = "dead"              # ejected (killed/stalled/lease-expired) — terminal

_SHED_PATIENCE = 50        # zero-progress router steps before shedding
_LEASE_STEPS = 8           # heartbeat silence (router steps) before eject
_DRAIN_PATIENCE = 64       # lossy-wire drain: resend rounds before eject


@dataclass
class FleetRequest:
    """Router-side request record — the client's view of the stream.

    ``tokens`` is the client-visible stream (exactly-once);
    ``emitted`` == len(tokens) survives failover while ``produced``
    counts the CURRENT replica life and resets to 0 at every dispatch,
    which is what makes replay dedup a pair of integer compares."""
    rid: str
    prompt: list[int]
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: int | None
    deadline_s: float | None
    max_queue_wait_s: float | None
    submit_seq: int
    tenant: int = 0            # fair-scheduling / quota scope on replicas
    priority: int = 0          # larger = more important (brownout shed order)
    tokens: list[int] = field(default_factory=list)
    emitted: int = 0           # tokens the client has seen (== len(tokens))
    produced: int = 0          # tokens produced by the current replica life
    finished: bool = False
    finish_reason: str | None = None
    replica: int | None = None  # current placement (None = router queue)
    replays: int = 0            # failover re-dispatches
    # --- disaggregated serving (SERVING.md "Disaggregated serving") ---
    handoff_src: int | None = None  # prefill replica that finished the phase
    handoff_wait_since: int = 0     # router step the wait (offer/pull) began
    handoff_fallback: bool = False  # degraded to plain colocated recompute
    handoff_committed: bool = False  # KV_COMMIT sent (held copy freed)
    # --- multi-tenant LoRA (SERVING.md "Multi-tenant LoRA serving") ---
    adapter: str = ""               # adapter digest (hex); "" = base model


@dataclass
class _Replica:
    idx: int
    engine: object
    state: str = CLOSED
    role: str = "colocated"     # "prefill" / "decode" under disagg placement
    consecutive_failures: int = 0
    opens: int = 0              # times the breaker opened (backoff exponent)
    backoff_until: int = 0      # router step when HALF_OPEN probing begins
    last_progress_step: int = 0
    dead_reason: str | None = None
    dump_path: str | None = None
    # --- transport-side membership state (SERVING.md "Fleet transport") ---
    epoch: int = 1              # this replica life; bumped at ejection
    applied_seq: int = 0        # result stream applied through this seq
    buffer: dict = field(default_factory=dict)   # out-of-order stream batches
    gauges: dict = field(default_factory=dict)   # last health payload seen
    last_heard: int = 0         # router step of the last applied message
    hb_seq: int = 0             # heartbeat seqnos sent
    hb_acked: int = 0           # highest heartbeat seq acked
    hb_sent_step: dict = field(default_factory=dict)  # seq -> step sent
    hb_suspected: int = 0       # highest seq already counted as a miss
    hb_next: int = 0            # next step a heartbeat is due
    live_rids: set = field(default_factory=set)  # requests placed here
    # multi-host: the dead process's classified fate ("signal:SIGKILL",
    # "exit:1", ...) read from its handle at ejection; None in-process
    exit_status: str | None = None


class FleetRouter:
    """Front-end over N homogeneous ``ServingEngine`` replicas.

    The public surface mirrors the single engine on purpose —
    ``submit`` (its ``add_request``), ``step``, ``stream``,
    ``run_to_completion``, ``drain``, ``attach_preemption_guard``,
    ``request``, ``stats`` — so a caller written against one engine
    upgrades to a fleet by swapping the constructor. The router keeps
    its OWN ``ServingMetrics`` fed only by client-delivered events, so
    its TTFT/ITL/goodput are the honest client-visible numbers across
    failovers (a replayed token that was suppressed never counts
    twice); per-replica engine metrics stay on the engines.

    All replica interaction goes through ``transport`` (default
    :class:`~.transport.LoopbackTransport` — synchronous, lossless,
    bitwise-identical to the pre-transport in-process fleet). The
    engine objects are retained ONLY for out-of-band introspection
    (``engines`` property, flight-recorder dumps, pool fault-path
    pinning) — never for serving-path calls.
    """

    def __init__(self, engines, max_queue_depth: int | None = None,
                 breaker_threshold: int = 3,
                 breaker_backoff_steps: int = 2,
                 breaker_backoff_max: int = 16,
                 shed_patience: int = _SHED_PATIENCE,
                 drain_patience: int = _DRAIN_PATIENCE,
                 clock=None, tracer=None, snapshot_store=None,
                 transport=None, lease_steps: int = _LEASE_STEPS,
                 heartbeat_interval: int = 1,
                 snapshot_fetch_interval: int = 4,
                 placement: str = "affinity",
                 disagg_prefill_frac: float = 0.5,
                 handoff_timeout_steps: int = 16,
                 reroll_interval: int = 16,
                 reroll_dwell: int = 3):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if placement not in ("affinity", "disagg"):
            raise ValueError(f"unknown placement mode {placement!r} "
                             "(expected 'affinity' or 'disagg')")
        if placement == "disagg" and len(engines) < 2:
            raise ValueError("placement='disagg' needs >= 2 replicas "
                             "(at least one per role)")
        self._replicas = [_Replica(i, e) for i, e in enumerate(engines)]
        for rep in self._replicas:
            pool = getattr(rep.engine, "pool", None)
            if pool is not None:
                # pin serving.alloc fault draws to this replica's index
                pool.fault_path = str(rep.idx)
        self.max_queue_depth = max_queue_depth
        self.breaker_threshold = breaker_threshold
        self.breaker_backoff_steps = breaker_backoff_steps
        self.breaker_backoff_max = breaker_backoff_max
        self.shed_patience = shed_patience
        # multi-host drains ride a real wire with real latencies: the
        # per-replica retry budget in drain() scales with the transport
        # instead of hard-wiring the loopback constant
        self.drain_patience = max(1, int(drain_patience))
        # --- disaggregated placement (SERVING.md "Disaggregated serving") ---
        self.placement = placement
        self.handoff_timeout_steps = max(1, int(handoff_timeout_steps))
        self.reroll_interval = int(reroll_interval)
        self.reroll_dwell = max(1, int(reroll_dwell))
        if placement == "disagg":
            n = len(self._replicas)
            n_pre = max(1, min(n - 1,
                               round(n * float(disagg_prefill_frac))))
            for rep in self._replicas:
                rep.role = "prefill" if rep.idx < n_pre else "decode"
        self._offers: dict[str, tuple] = {}      # rid -> (src idx, snapshot)
        self._handoff_delayed: list[tuple] = []  # (release, src, rid, snap)
        self._reroll_pressure = 0                # signed dwell counter
        self.lease_steps = max(1, int(lease_steps))
        self.heartbeat_interval = max(1, int(heartbeat_interval))
        self.snapshot_fetch_interval = int(snapshot_fetch_interval)
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = ServingMetrics(clock)     # client-visible stream
        self.fleet_metrics = FleetMetrics()
        self._records: dict[str, FleetRequest] = {}
        self._pending: list[FleetRequest] = []   # router queue, submit order
        # bounded-replay failover (serving/snapshot.py): the shared
        # store the replicas capture into — it models the off-replica
        # durable medium, so a replica's death never takes its
        # requests' snapshots with it. Auto-discovered from the engines
        # when not passed explicitly; None -> every failover is a full
        # replay from token 0 (the pre-snapshot behaviour).
        self._snapshot_store = snapshot_store
        if self._snapshot_store is None:
            for e in engines:
                store = getattr(e, "snapshot_store", None)
                if store is not None:
                    self._snapshot_store = store
                    break
        # rid -> ejection time: open recovery windows, closed by the
        # first FRESH post-recovery token (time-to-first-recovered-token)
        self._recovering: dict[str, float] = {}
        self._submit_seq = 0
        self._steps = 0
        # router-step duration EMA (metrics clock): the timing input to
        # the deterministic retry_after_s hint on fleet-level sheds
        self._step_dt_ema: float | None = None
        self._idle_steps = 0
        self._draining = False
        self._guard = None
        self.last_drain_events: list[dict] = []
        # --- the wire (SERVING.md "Fleet transport & membership") ---
        self._transport = (transport if transport is not None
                           else LoopbackTransport())
        self._transport.bind("router")           # inbox endpoint
        # multi-host attach (serving/replica_host.py): an engine with
        # ``is_remote`` is a handle to a replica living in another OS
        # process — its EngineServer runs THERE, bound to the same
        # "replica:i" name on the far side of the socket, so the router
        # builds no local server for it and speaks purely via the wire
        self._servers = [None if getattr(e, "is_remote", False)
                         else EngineServer(i, e, self._transport)
                         for i, e in enumerate(engines)]
        # submits in flight: rid -> (replica idx, attempt, sent Message).
        # A pinned submit is retransmitted verbatim until its reply
        # lands — never re-placed elsewhere, so a delayed reply can
        # never cause a double placement.
        self._outstanding: dict[str, tuple] = {}
        self._attempts: dict[str, int] = {}
        self._submit_outcomes: dict[tuple, str] = {}  # (rid, attempt) -> ..
        self._hb_rtt: list[int] = []             # heartbeat RTTs, in steps
        # gauge bootstrap: a direct pre-network read at construction
        # (the wire has not carried anything yet); replicas whose engine
        # keeps a PRIVATE snapshot store get harvested over the wire
        for rep, srv in zip(self._replicas, self._servers):
            if srv is not None:
                rep.gauges = srv.gauges()
            else:
                # remote replica: best-effort gauge seed over the wire
                # (None on timeout — the first heartbeat ack fills it)
                rep.gauges = (self._transport.query(
                    f"replica:{rep.idx}", "gauges", {}) or rep.gauges)
            # phase heartbeats off the shared deterministic jitter so a
            # large fleet does not burst every lease in the same step
            rep.hb_next = deterministic_jitter(
                f"fleet-hb:{rep.idx}", self.heartbeat_interval)
        self._fetch_idx = [
            i for i, e in enumerate(engines)
            if (getattr(e, "snapshot_store", None) is not None
                and e.snapshot_store is not self._snapshot_store)
            # a remote replica's store is BY CONSTRUCTION private (it
            # lives in another process): harvest it whenever the router
            # keeps a store of its own to harvest into
            or (getattr(e, "is_remote", False)
                and self._snapshot_store is not None)]

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               sampling: SamplingParams | None = None,
               eos_token_id: int | None = None,
               rid: str | None = None,
               deadline_s: float | None = None,
               max_queue_wait_s: float | None = None,
               tenant: int = 0, priority: int = 0,
               adapter: str = "") -> str:
        """Fleet admission. A full global queue sheds with
        :class:`FleetOverloadedError` (carrying ``retry_after_s``, the
        router's drain-rate estimate — RESILIENCE.md "Overload
        playbook"); a request no replica could EVER run raises
        :class:`RequestTooLargeError` here, before it occupies queue
        space anywhere (homogeneous fleet — replica 0's
        ``admission_check``, probed over the transport's advisory
        channel, speaks for all; an unreachable replica 0 skips the
        probe and dispatch classification covers it). ``tenant`` /
        ``priority`` ride the record to every placement (fair
        scheduling, quotas and brownout shed order on the replicas —
        SERVING.md "Overload control & tenant fairness"). ``adapter``
        (a LoRA adapter digest, hex) rides the record too: placement
        gains an adapter-residency affinity bonus and every failover
        replay re-binds the same adapter — a stream never silently
        resumes on base weights (SERVING.md "Multi-tenant LoRA
        serving"). Placement happens at the next ``step()``, not here:
        dispatch failures are the router's to retry, never the
        client's."""
        if self._draining:
            raise EngineDrainingError(
                "fleet is draining (preempted or shut down); "
                "retry against another fleet")
        if (self.max_queue_depth is not None
                and len(self._pending) >= self.max_queue_depth):
            retry = self._retry_after_s()
            self.fleet_metrics.bump("shed")
            self.metrics.on_reject("queue_full")
            self.metrics.on_shed(int(tenant), int(priority))
            raise FleetOverloadedError(
                f"fleet queue at max_queue_depth={self.max_queue_depth}; "
                f"request shed (every replica saturated — retry after "
                f"~{retry:.3f}s with backoff, or scale out)",
                retry_after_s=retry)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        res = self._transport.query(
            "replica:0", "admission_check",
            {"prompt_len": len(prompt), "max_new_tokens": max_new_tokens})
        if res is not None and not res.get("ok", True):
            self.metrics.on_reject("too_large")
            raise RequestTooLargeError(
                res.get("detail", "admission refused"))
        rid = rid if rid is not None else f"fleet-req-{self._submit_seq}"
        if rid in self._records:
            raise ValueError(f"duplicate request id {rid!r}")
        rec = FleetRequest(rid=rid, prompt=prompt,
                           max_new_tokens=max_new_tokens,
                           sampling=sampling or SamplingParams(),
                           eos_token_id=eos_token_id,
                           deadline_s=deadline_s,
                           max_queue_wait_s=max_queue_wait_s,
                           submit_seq=self._submit_seq,
                           tenant=int(tenant), priority=int(priority),
                           adapter=str(adapter or ""))
        self._submit_seq += 1
        self._records[rid] = rec
        self._pending.append(rec)
        self.metrics.on_arrival(rid, tenant=int(tenant),
                                priority=int(priority))
        self.tracer.instant("submit", track="fleet", rid=rid,
                            queue=len(self._pending))
        return rid

    def _retry_after_s(self) -> float:
        """Deterministic fleet drain-rate estimate behind the
        ``retry_after_s`` hint on FleetOverloadedError and router shed
        events: service tokens held by the router queue over the live
        replicas' combined per-step token capacity (from the gauges the
        transport carried last), scaled by the router-step-duration EMA
        (metrics clock). 0.0 before the first timed step — honest "no
        data yet", never a made-up constant."""
        if self._step_dt_ema is None or self._step_dt_ema <= 0.0:
            return 0.0
        tokens = sum(len(r.prompt) + r.max_new_tokens
                     for r in self._pending)
        cap = 0
        for rep in self._replicas:
            if rep.state == DEAD:
                continue
            tc = rep.gauges.get("token_capacity")
            cap += int(tc) if tc is not None else 1
        return tokens / max(cap, 1) * self._step_dt_ema

    # ------------------------------------------------------------------
    # stepping
    # ------------------------------------------------------------------

    def step(self) -> list[dict]:
        """One router iteration: transport tick + late-arrival apply,
        chaos/health/lease sweep, placement of the router queue, one
        STEP command per replica believed to hold work (applying the
        result streams — ejecting and failing over any replica that
        reports death or goes silent past its lease), and exactly-once
        translation of replica events into client events. Bounded work
        — a replica that cannot accept work this step is retried next
        step, never spun on."""
        t_step0 = self.metrics.now()
        events: list[dict] = []
        self._progress_flag = False
        self._submit_outcomes.clear()
        self._transport.tick(self._steps)
        self._pump_and_apply(events)     # delayed/held arrivals
        self._kill_sweep()
        self._health_sweep(events)
        self._pump_and_apply(events)     # heartbeat acks (loopback: now)
        self._snapshot_fetch()
        self._handoff_sweep()
        self._reroll_sweep()
        self._dispatch(events)
        if events:
            self._progress_flag = True
        for rep in list(self._replicas):
            if rep.state == DEAD or not rep.live_rids:
                continue
            self._transport.send(Message.make(
                "STEP", "router", f"replica:{rep.idx}", epoch=rep.epoch,
                payload={"router_step": self._steps,
                         "ack": rep.applied_seq}))
            # loopback: the results of exactly this step apply here,
            # preserving the pre-transport per-replica translate order
            self._pump_and_apply(events)
        self._steps += 1
        if self._progress_flag or not self._pending:
            self._idle_steps = 0
        else:
            self._idle_steps += 1
        alive = [r for r in self._replicas if r.state != DEAD]
        if self._pending and (not alive
                              or self._idle_steps >= self.shed_patience):
            # no-hang guarantee: nothing can place these — classify and
            # finish them instead of spinning (terminal, retryable at
            # the client since nothing was computed)
            for rec in list(self._pending):
                self._finish_record(rec, "shed", events)
            self._pending.clear()
        dt = self.metrics.now() - t_step0
        if dt > 0.0:
            self._step_dt_ema = (dt if self._step_dt_ema is None
                                 else 0.8 * self._step_dt_ema + 0.2 * dt)
        return events

    def has_work(self) -> bool:
        if self._pending:
            return True
        return any(rep.state != DEAD and rep.live_rids
                   for rep in self._replicas)

    def stream(self):
        """Drive the fleet to completion, yielding client events —
        ``{"rid", "token", "finished", "finish_reason", "replica"}`` —
        exactly once each, in production order. On a tripped preemption
        guard the fleet drains and the terminal events are yielded."""
        while self.has_work():
            if self._preemption_pending():
                self.drain()
                yield from self.last_drain_events
                return
            yield from self.step()

    def run_to_completion(self, max_steps: int | None = None) -> dict:
        """Drain the fleet; {rid: client-visible token list}. Raises
        after ``max_steps`` router steps — the chaos suites' hang
        tripwire."""
        steps = 0
        while self.has_work():
            if self._preemption_pending():
                self.drain()
                break
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {steps} router steps")
        return {rid: list(r.tokens) for rid, r in self._records.items()}

    # ------------------------------------------------------------------
    # the receive path: ordered streams, epoch fencing, application
    # ------------------------------------------------------------------

    def _pump_and_apply(self, sink: list[dict]) -> None:
        """Run transport deliveries, then apply everything addressed to
        the router: epoch-fence, stream-order and dedup each message,
        translating replica events into ``sink``."""
        self._transport.pump()
        for msg in self._transport.recv("router"):
            self._on_message(msg, sink)

    def _on_message(self, msg: Message, sink: list[dict]) -> None:
        try:
            idx = int(msg.src.split(":", 1)[1])
            rep = self._replicas[idx]
        except (IndexError, ValueError):
            return
        # THE fence: traffic from a dead replica, or stamped with an
        # epoch that is not this replica's current life, is zombie
        # output from before a partition/ejection — counted, dropped,
        # never applied. This is what makes double emission impossible.
        if rep.state == DEAD or msg.epoch != rep.epoch:
            self.fleet_metrics.bump("stale_epoch_discarded")
            return
        rep.last_heard = self._steps          # any applied message renews
        if msg.kind == "HEARTBEAT_ACK":
            p = msg.payload()
            if p["hb_seq"] > rep.hb_acked:    # freshest ack wins
                rep.hb_acked = p["hb_seq"]
                rep.gauges = p["gauges"]
                self._hb_rtt.append(self._steps - int(p["sent_step"]))
                if len(self._hb_rtt) > 1024:
                    del self._hb_rtt[:512]
            return
        # ordered result stream: apply in seq order, buffer the future,
        # suppress what was already applied (at-least-once -> once)
        if msg.seq <= rep.applied_seq or msg.seq in rep.buffer:
            self.fleet_metrics.bump("duplicates_suppressed")
            return
        rep.buffer[msg.seq] = msg
        while rep.applied_seq + 1 in rep.buffer:
            m = rep.buffer.pop(rep.applied_seq + 1)
            rep.applied_seq += 1
            self._apply(rep, m, sink)
            if rep.state == DEAD:             # applying ejected it
                break

    def _apply(self, rep: _Replica, msg: Message,
               sink: list[dict]) -> None:
        p = msg.payload()
        if "gauges" in p:
            rep.gauges = p["gauges"]
        kind = msg.kind
        if kind == "SUBMIT_REPLY":
            self._apply_submit_reply(rep, p, sink)
        elif kind in ("STEP_RESULTS", "DRAIN_RESULTS"):
            if p["events"]:
                rep.last_progress_step = self._steps
                self._progress_flag = True
            self._translate(rep, p["events"], sink)
        elif kind == "ERROR":
            self._eject(rep, p["reason"], snapshot=p.get("snapshot"))
        elif kind == "KV_OFFER":
            self._apply_offer(rep, msg)
        elif kind == "SNAPSHOT_DATA":
            store = self._snapshot_store
            if store is not None:
                # the transport already stripped any snapshot that
                # failed its digest re-verify (counted corrupt_dropped)
                for snap in msg.snaps:
                    rec = self._records.get(snap.rid)
                    if rec is not None and not rec.finished:
                        store.put(snap.rid, snap)

    def _apply_submit_reply(self, rep: _Replica, p: dict,
                            sink: list[dict]) -> None:
        rid, attempt = p["rid"], p["attempt"]
        entry = self._outstanding.get(rid)
        if entry is None or entry[0] != rep.idx or entry[1] != attempt:
            return   # a cancelled/superseded attempt — already failed over
        was_pull = entry[2].kind == "KV_PULL"
        del self._outstanding[rid]
        rec = self._records.get(rid)
        if rec is None or rec.finished:
            return
        if not p["ok"]:
            if p.get("error") == "RequestTooLargeError":
                # cannot happen after submit-time admission_check on a
                # homogeneous fleet, but a duck-typed engine may
                # disagree: classify, never retry (retryable=False)
                self._finish_record(rec, "rejected_too_large", sink)
                if rec in self._pending:
                    self._pending.remove(rec)
                self._submit_outcomes[(rid, attempt)] = "finished"
            else:
                # retryable territory (queue full / draining / injected
                # dispatch fault): breaker data point, record stays
                # queued for the next candidate / next step
                self._breaker_failure(rep)
                self._submit_outcomes[(rid, attempt)] = "retry"
            return
        self._breaker_success(rep)
        rec.replica = rep.idx
        # the replica's first emission is token index len(snap.tokens):
        # seeding produced keeps the dedup's position arithmetic exact
        rec.produced = (int(p.get("restored", 0))
                        if p.get("used_snapshot") else 0)
        if rec.replays:
            fm = self.fleet_metrics
            # THE bounded-vs-full A/B number: tokens this failover still
            # re-produces (full replay pays the whole emitted count)
            fm.bump("recovery_replayed_tokens", rec.emitted - rec.produced)
            if p.get("used_snapshot"):
                fm.bump("snapshot_restores")
                fm.bump("recovery_restored_tokens", rec.produced)
            elif self._snapshot_store is not None:
                fm.bump("snapshot_fallbacks")
        if was_pull:
            # the decode replica landed (or refused) the offered KV:
            # count the pull, mark the handoff-transfer end for the
            # TTFT breakdown, and commit so the prefill side frees its
            # held copy. kv_injected=False means the digest gate
            # refused the payload and the decode replica recomputed the
            # prefill itself — slower, never wrong.
            self.fleet_metrics.bump("handoff_pulls")
            if not p.get("kv_injected", True):
                self.fleet_metrics.bump("handoff_corrupt")
            self.metrics.on_handoff_landed(rid)
            self._commit_handoff(rec)
        self.metrics.on_admit(rid)
        self.fleet_metrics.bump("dispatched")
        if rec.replays:
            self.fleet_metrics.bump("replayed_requests")
        rep.live_rids.add(rid)
        if rec in self._pending:
            self._pending.remove(rec)
        self.tracer.instant("dispatch", track="fleet", rid=rid,
                            replica=rep.idx, replay=rec.replays,
                            restored=rec.produced)
        self._submit_outcomes[(rid, attempt)] = "placed"

    # ------------------------------------------------------------------
    # drain / preemption
    # ------------------------------------------------------------------

    def drain(self, timeout_s: float | None = None) -> dict:
        """Fleet-wide graceful shutdown: shed the router queue as
        retriable ``preempted`` outcomes (nothing was computed for
        them), then DRAIN every replica believed to hold work — running
        requests decode to their own finish within ``timeout_s`` (per
        replica, on its metrics clock) and their events flow through
        the exactly-once translation like any other step. Over a lossy
        wire the DRAIN is retransmitted with the transport clock
        advancing; a replica that never answers is ejected
        (``died_in_drain``) and its requests classify as preempted.
        Returns {rid: {finish_reason, tokens, retriable}} over ALL
        fleet requests; terminal events land in ``last_drain_events``."""
        events: list[dict] = []
        self._draining = True
        for rec in list(self._pending):
            self._finish_record(rec, "preempted", events)
        self._pending.clear()
        for rep in self._replicas:
            if rep.state == DEAD or not rep.live_rids:
                continue
            msg = Message.make(
                "DRAIN", "router", f"replica:{rep.idx}", epoch=rep.epoch,
                payload={"timeout_s": timeout_s, "ack": rep.applied_seq})
            self._transport.send(msg)
            self._pump_and_apply(events)
            tries = 0
            while rep.state != DEAD and rep.live_rids:
                tries += 1
                if tries > self.drain_patience:
                    self._eject(rep, "died_in_drain")
                    break
                # lossy wire: advance the injectable clock so delayed /
                # held deliveries release, and retransmit (the server's
                # drain is a one-shot latch — duplicates are cheap)
                self._steps += 1
                self._transport.tick(self._steps)
                self._transport.send(msg)
                self._pump_and_apply(events)
        # anything still unfinished (its replica died mid-drain and
        # there is nowhere left to replay) is preempted: retryable,
        # nothing the client saw is lost
        for rec in self._records.values():
            if not rec.finished:
                self._finish_record(rec, "preempted", events)
        self._pending.clear()    # eject-failover re-queues are moot now
        self.last_drain_events = events
        self.tracer.instant("fleet_drain", track="fleet",
                            requests=len(self._records))
        return {rid: {"finish_reason": rec.finish_reason,
                      "tokens": list(rec.tokens),
                      "retriable": rec.finish_reason in ("preempted",
                                                         "shed")}
                for rid, rec in self._records.items()}

    def attach_preemption_guard(self, guard=None):
        """Fleet-wide SIGTERM handling: one guard covers every replica —
        ``stream``/``run_to_completion`` notice ``guard.preempted`` at a
        router-step boundary and ``drain()`` the whole fleet (structured
        retry-elsewhere outcomes, same contract as the single engine)."""
        if guard is None:
            from ..distributed import PreemptionGuard
            guard = PreemptionGuard()
        self._guard = guard
        return guard

    def _preemption_pending(self) -> bool:
        return (self._guard is not None and self._guard.preempted
                and not self._draining)

    # ------------------------------------------------------------------
    # health / membership / breaker
    # ------------------------------------------------------------------

    def health(self, idx: int) -> dict:
        """One replica's health view — entirely from the gauges its
        server piggybacks on heartbeat acks and stream replies (a dead
        or partitioned replica shows its last-known gauges): *ready*
        (would accept a dispatch now — queue/pool pressure + breaker),
        *live* (step progress; vacuously true while it has nothing to
        do), and the breaker/lease bookkeeping an operator alerts on."""
        rep = self._replicas[idx]
        g = rep.gauges
        qd = int(g.get("queue_depth", 0))
        running = int(g.get("running", 0))
        has_work = bool(rep.live_rids) or qd + running > 0
        return {
            "replica": idx,
            "state": rep.state,
            # "colocated" outside disagg placement; "prefill"/"decode"
            # under it (may change over a replica's life — re-rolling)
            "role": rep.role,
            "ready": self._ready(rep),
            "live": (rep.state != DEAD
                     and (not has_work
                          or self._steps - rep.last_progress_step
                          <= self.shed_patience)),
            "queue_depth": qd,
            "running": running,
            "pool_utilization": float(g.get("pool_utilization", 0.0)),
            # a replica is a TP *group*: tp devices serving one engine.
            # One device failing takes the whole group — the breaker /
            # failover-replay path below is the same either way
            # (RESILIENCE.md), this gauge just sizes the blast radius.
            "tp_degree": int(g.get("tp_degree", 1)),
            "pp_degree": int(g.get("pp_degree", 1)),
            # overload-control gauge: which brownout rung this replica
            # is on (0 = normal service; engines without the ladder
            # always read 0)
            "brownout_level": int(g.get("brownout_level", 0)),
            "consecutive_failures": rep.consecutive_failures,
            "breaker_opens": rep.opens,
            "backoff_remaining": max(0, rep.backoff_until - self._steps),
            "dead_reason": rep.dead_reason,
            "flight_recorder": rep.dump_path,
            # membership gauges (SERVING.md "Fleet transport")
            "epoch": rep.epoch,
            "lease_age": max(0, self._steps - rep.last_heard),
            # multi-host identity (SERVING.md "Multi-host serving"):
            # where this replica actually runs — its OS pid (local
            # servers report the router's own; remote ones theirs, via
            # gauges/handle) and socket address when one exists
            "pid": (getattr(rep.engine, "pid", None)
                    or g.get("pid")),
            "addr": self._replica_addr(rep),
            # post-mortem classification of a dead replica process
            # ("signal:SIGKILL", "exit:1", ...); None while alive or
            # for in-process replicas, which have no exit to classify
            "exit_status": rep.exit_status,
        }

    def _replica_addr(self, rep: _Replica):
        peer_addr = getattr(self._transport, "peer_addr", None)
        if peer_addr is not None:
            addr = peer_addr(f"replica:{rep.idx}")
            if addr is not None:
                return addr
        return getattr(rep.engine, "addr", None)

    def _ready(self, rep: _Replica) -> bool:
        if rep.state == DEAD or rep.state == OPEN:
            return False
        g = rep.gauges
        if g.get("draining"):
            return False
        mqd = g.get("max_queue_depth")
        if mqd is not None and int(g.get("queue_depth", 0)) >= int(mqd):
            return False
        return True

    def _health_sweep(self, events: list[dict]) -> None:
        """Lease-based membership: advance breaker timers, fire the
        ``fleet.health`` site per live replica (an injected health
        failure counts as a transient breaker failure, exactly like a
        failed dispatch), suspect replicas whose heartbeats go unacked
        (one breaker failure per missed seq), expire the lease of a
        replica silent past ``lease_steps`` (eject + failover), and
        send the next heartbeat when due."""
        hb_miss = max(2, self.lease_steps // 2)
        for rep in self._replicas:
            if rep.state == DEAD:
                continue
            if rep.state == OPEN and self._steps >= rep.backoff_until:
                rep.state = HALF_OPEN
                self.fleet_metrics.bump("probes")
                self.tracer.instant("breaker_half_open", track="fleet",
                                    replica=rep.idx)
            try:
                _fault.trip("fleet.health", step=self._steps,
                            path=str(rep.idx))
            except _fault.FaultInjected:
                self._breaker_failure(rep)
            # missed-ack suspicion: an unacked heartbeat past half the
            # lease is a breaker data point (per missed seq, once)
            for seq in sorted(rep.hb_sent_step):
                if seq <= rep.hb_acked:
                    del rep.hb_sent_step[seq]
                    continue
                if (seq > rep.hb_suspected
                        and self._steps - rep.hb_sent_step[seq] >= hb_miss):
                    rep.hb_suspected = seq
                    self.tracer.instant("lease_suspect", track="fleet",
                                        replica=rep.idx, hb_seq=seq)
                    self._breaker_failure(rep)
                    if rep.state == DEAD:
                        break
            if rep.state == DEAD:
                continue
            # lease expiry: total silence -> the replica is gone (or
            # partitioned, which over this wire is the same thing)
            if self._steps - rep.last_heard > self.lease_steps:
                self.fleet_metrics.bump("lease_expirations")
                self._eject(rep, "lease_expired")
                continue
            if self._steps >= rep.hb_next:
                rep.hb_seq += 1
                rep.hb_sent_step[rep.hb_seq] = self._steps
                rep.hb_next = self._steps + self.heartbeat_interval
                self._transport.send(Message.make(
                    "HEARTBEAT", "router", f"replica:{rep.idx}",
                    epoch=rep.epoch,
                    payload={"hb_seq": rep.hb_seq,
                             "sent_step": self._steps,
                             "ack": rep.applied_seq}))

    def _breaker_failure(self, rep: _Replica) -> None:
        rep.consecutive_failures += 1
        if rep.state == HALF_OPEN or (
                rep.state == CLOSED
                and rep.consecutive_failures >= self.breaker_threshold):
            rep.opens += 1
            rep.state = OPEN
            backoff = min(
                self.breaker_backoff_steps * (2 ** (rep.opens - 1)),
                self.breaker_backoff_max)
            rep.backoff_until = self._steps + backoff + self._jitter(
                rep.idx, rep.opens, backoff)
            self.fleet_metrics.bump("breaker_opens")
            self.tracer.instant("breaker_open", track="fleet",
                                replica=rep.idx, opens=rep.opens,
                                until=rep.backoff_until)

    def _breaker_success(self, rep: _Replica) -> None:
        rep.consecutive_failures = 0
        if rep.state == HALF_OPEN:
            rep.state = CLOSED
            self.tracer.instant("breaker_close", track="fleet",
                                replica=rep.idx)

    @staticmethod
    def _jitter(idx: int, opens: int, backoff: int) -> int:
        """Deterministic backoff jitter in [0, backoff) — delegates to
        the shared :func:`~.transport.deterministic_jitter` (the same
        helper that phases heartbeats), preserving the exact historical
        key string so chaos runs replay bit-identically across PRs."""
        return deterministic_jitter(f"fleet-jitter:{idx}:{opens}", backoff)

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------

    def _dispatch(self, events: list[dict]) -> None:
        """Place router-queued records onto ready replicas, FCFS by
        submit order. Best-effort prefix-cache affinity first (largest
        ``match_prefix`` hit via the transport's advisory query), then
        least-loaded; every typed failure reply is a breaker data point
        and the record simply stays queued for the next step — bounded
        work, no spinning. A submit whose reply has not arrived stays
        PINNED to its replica (retransmitted verbatim each step) so a
        delayed reply can never race a second placement.

        Under ``placement="disagg"`` each record rides one of four
        LANES, picked by its handoff state: a held KV offer dispatches
        a ``KV_PULL`` to a decode-role replica (original submit order —
        ``_pending`` is submit_seq-sorted, so re-admission preserves
        arrival order); a record whose prefill finished but whose offer
        has not arrived waits (the handoff sweep owns its timeout); a
        fresh record places STRICTLY on a prefill-role replica with
        ``prefill_only`` (waiting for a busy specialist beats smearing
        prefill work across decode replicas — unless the role is
        extinct, in which case it colocates); everything else (failover
        replays, handoff fallbacks) takes the plain colocated lane."""
        if not self._pending:
            return
        disagg = self.placement == "disagg"
        for rec in list(self._pending):
            if rec not in self._pending:
                continue     # resolved while pumping an earlier submit
            if rec.finished or rec.replica is not None:
                self._pending.remove(rec)
                continue
            if rec.rid in self._outstanding:
                idx, attempt, msg = self._outstanding[rec.rid]
                self._transport.send(msg)       # retransmit the pinned submit
                self._pump_and_apply(events)
                if self._submit_outcomes.get((rec.rid, attempt)) != "retry":
                    continue    # placed/finished (applied) or still pinned
            kind, snap, prefill_only = "SUBMIT", None, False
            candidates = [rep for rep in self._replicas
                          if self._ready(rep)]
            if disagg:
                offer = self._offers.get(rec.rid)
                if offer is not None and not rec.handoff_fallback:
                    # pull lane: land the offered KV on a decode replica
                    # (any ready replica if the decode role is starved —
                    # the pages inject the same either way)
                    kind = "KV_PULL"
                    snap = ((self._usable_snapshot(rec) if rec.replays
                             else None) or offer[1])
                    decode = [rep for rep in candidates
                              if rep.role == "decode"]
                    candidates = decode or candidates
                elif (rec.handoff_src is not None
                        and not rec.handoff_fallback):
                    continue   # prefill done, offer in flight — the
                               # handoff sweep owns the timeout
                elif (not rec.replays and not rec.handoff_fallback
                        and any(r.state != DEAD and r.role == "prefill"
                                for r in self._replicas)):
                    prefill_only = True
                    candidates = [rep for rep in candidates
                                  if rep.role == "prefill"]
            if not candidates:
                if disagg:
                    continue  # lanes differ per record — try the next
                break  # nothing can take the head now — FCFS, try later
            ordered = sorted(
                candidates,
                key=lambda rep: (-self._affinity(rep, rec),
                                 self._load(rep), rep.idx))
            for rep in ordered:
                out = self._submit_to(rec, rep, events, kind=kind,
                                      snap=snap, prefill_only=prefill_only)
                if out in ("placed", "finished") or rec.finished:
                    break
                if out is None:
                    break       # no reply yet — pinned to this replica
                # out == "retry": try the next candidate this same step

    @staticmethod
    def _load(rep: _Replica) -> int:
        g = rep.gauges
        return int(g.get("queue_depth", 0)) + int(g.get("running", 0))

    def _affinity(self, rep: _Replica, rec: FleetRequest) -> int:
        """Cached-prefix tokens this replica's pool already holds for
        the prompt — the transport's advisory query against the
        content-hash index (0 for an unreachable replica: a partition
        costs affinity, never correctness). An adapter-bound request
        adds an ADAPTER residency bonus (SERVING.md "Multi-tenant LoRA
        serving"): a replica whose AdapterPool already holds the
        adapter's weights resident skips the host-tier stream-in, worth
        more than a few cached prompt tokens — the server weighs it as
        one full page of cached tokens. Prompt-prefix hits can only
        come from same-adapter requests anyway (the prefix index is
        namespaced per adapter), so the two signals compose instead of
        conflicting."""
        payload = {"prompt": rec.prompt}
        if rec.adapter:
            payload["adapter"] = rec.adapter
        res = self._transport.query(f"replica:{rep.idx}", "affinity",
                                    payload)
        return int(res["cached_tokens"]) if res else 0

    def _usable_snapshot(self, rec: FleetRequest):
        """The record's latest VERIFIED snapshot, iff seeding from it
        is provably safe: its token prefix must already be in the
        client-delivered stream (len <= emitted, bitwise equal) —
        seeded tokens are never re-emitted by the engine, so a token
        beyond the delivered stream would silently vanish. Anything
        else (missing, digest-corrupt, ahead of the client) returns
        None and the failover degrades to full replay from token 0 —
        slower, never wrong."""
        store = self._snapshot_store
        if store is None:
            return None
        snap = store.get(rec.rid)   # digest-re-verified; corrupt -> None
        if snap is None:
            return None
        n = len(snap.tokens)
        if n > rec.emitted or list(snap.tokens) != rec.tokens[:n]:
            return None
        return snap

    def _submit_to(self, rec: FleetRequest, rep: _Replica,
                   events: list[dict], kind: str = "SUBMIT",
                   snap=None, prefill_only: bool = False) -> str | None:
        """Send one SUBMIT/KV_PULL attempt over the wire and (when the
        reply is synchronous — loopback) resolve its outcome:
        ``"placed"``, ``"retry"`` (typed retryable failure — breaker
        fed, caller tries the next candidate), ``"finished"``
        (classified non-retryable), or None (reply in flight — the
        submit is pinned and retransmitted until it resolves)."""
        attempt = self._attempts.get(rec.rid, 0) + 1
        self._attempts[rec.rid] = attempt
        try:
            _fault.trip("fleet.dispatch", step=self._steps, path=rec.rid)
        except _fault.FaultInjected:
            self._breaker_failure(rep)
            return "retry"
        # bounded-replay failover: a replayed record with a usable
        # snapshot ships it on the message (the snapshot re-verifies its
        # own digests at receive); the server restores KV + seeded
        # tokens and replays only the delta since capture. The seeded
        # tokens flow through the SAME emitted-vs-produced dedup via the
        # produced counter, so client streams stay exactly-once and
        # bitwise. tenant/priority ride every placement (fair
        # scheduling, quotas and brownout shed order on the replica —
        # restore included, so SURVIVOR quotas govern failover replay).
        # A KV_PULL is the same exchange seeded with the handoff
        # snapshot the dispatch lane chose; prefill_only marks the
        # disagg prefill lane (the engine exports KV instead of
        # emitting a first token).
        if kind == "SUBMIT" and snap is None and rec.replays:
            snap = self._usable_snapshot(rec)
        payload = {"attempt": attempt, "prompt": rec.prompt,
                   "max_new_tokens": rec.max_new_tokens,
                   "sampling": asdict(rec.sampling),
                   "eos_token_id": rec.eos_token_id,
                   "deadline_s": rec.deadline_s,
                   "max_queue_wait_s": rec.max_queue_wait_s,
                   "tenant": rec.tenant, "priority": rec.priority,
                   "ack": rep.applied_seq}
        if rec.adapter:
            payload["adapter"] = rec.adapter
        if prefill_only:
            payload["prefill_only"] = True
        if kind == "KV_PULL":
            payload["handoff_pull"] = True
        msg = Message.make(
            kind, "router", f"replica:{rep.idx}", epoch=rep.epoch,
            rid=rec.rid, payload=payload,
            snaps=(snap,) if snap is not None else ())
        self._outstanding[rec.rid] = (rep.idx, attempt, msg)
        self._transport.send(msg)
        self._pump_and_apply(events)
        return self._submit_outcomes.get((rec.rid, attempt))

    # ------------------------------------------------------------------
    # failure handling
    # ------------------------------------------------------------------

    def _kill_sweep(self) -> None:
        """The ``fleet.replica_kill`` chaos site: an armed ``raise``
        matching a replica index kills that replica at this step
        boundary (between engine steps — never mid-step, which is what
        keeps replay exactly-once)."""
        if _fault.active_plan() is None:
            return
        for rep in self._replicas:
            if rep.state == DEAD:
                continue
            try:
                _fault.trip("fleet.replica_kill", step=self._steps,
                            path=str(rep.idx))
            except _fault.FaultInjected:
                self._eject(rep, "killed")

    def kill_replica(self, idx: int, reason: str = "killed") -> None:
        """Operational/chaos API: eject a replica NOW and fail its
        in-flight requests over (equivalent to a replica_kill fault)."""
        rep = self._replicas[idx]
        if rep.state != DEAD:
            self._eject(rep, reason)

    def _eject(self, rep: _Replica, reason: str,
               snapshot: dict | None = None) -> None:
        """Replica death: flight-recorder dump, DEAD state, epoch bump
        (the fence — any message the zombie sends afterwards carries a
        stale epoch and is discarded), and failover — every live
        request it held, from the ROUTER's records (the dead replica
        cannot be asked over a partition), goes back to the router
        queue in submit order for deterministic replay on a healthy
        replica. A best-effort FENCE tells the replica's server to
        refuse its old epoch too (defense in depth for commands still
        in flight)."""
        rep.state = DEAD
        rep.dead_reason = reason
        self.fleet_metrics.bump("ejections")
        # multi-host post-mortem: a remote handle can classify how the
        # process actually died (SIGKILL vs SIGTERM vs clean exit) —
        # evidence the lease expiry alone cannot carry
        post_mortem = getattr(rep.engine, "post_mortem", None)
        if post_mortem is not None:
            try:
                rep.exit_status = post_mortem()
            except Exception:  # noqa: BLE001 — diagnosis is best-effort
                rep.exit_status = None
        recorder = getattr(rep.engine, "flight_recorder", None)
        if recorder is not None:
            try:
                rep.dump_path = recorder.dump(
                    f"fleet_eject_{reason}",
                    snapshot={"replica": rep.idx, "reason": reason,
                              **(snapshot or {})})
            except OSError:
                rep.dump_path = None
        self.tracer.instant("replica_eject", track="fleet",
                            replica=rep.idx, reason=reason)
        old_epoch = rep.epoch
        rep.epoch += 1
        rep.buffer.clear()
        rep.live_rids = set()
        # cancel submits pinned to it: their records are still pending
        # and will re-place on a healthy replica with a fresh attempt
        for rid in [r for r, e in self._outstanding.items()
                    if e[0] == rep.idx]:
            del self._outstanding[rid]
        survivors = sorted(
            (r for r in self._records.values()
             if r.replica == rep.idx and not r.finished),
            key=lambda r: r.submit_seq)
        for rec in survivors:
            rec.replica = None
            rec.produced = 0
            rec.replays += 1
            # open the recovery window (closed by the first fresh
            # token); a second ejection mid-recovery keeps the original
            # start so TTFRT measures the whole client-visible gap
            self._recovering.setdefault(rec.rid, self.metrics.now())
            self.fleet_metrics.bump("failovers")
            keys = [r.submit_seq for r in self._pending]
            self._pending.insert(
                bisect.bisect_left(keys, rec.submit_seq), rec)
            self.tracer.instant("failover", track="fleet", rid=rec.rid,
                                emitted=rec.emitted, replica=rep.idx)
        self._transport.send(Message.make(
            "FENCE", "router", f"replica:{rep.idx}", epoch=old_epoch,
            payload={"reason": reason}))

    # ------------------------------------------------------------------
    # disaggregated serving: KV handoff + elastic role re-rolling
    # ------------------------------------------------------------------

    def _apply_offer(self, rep: _Replica, msg: Message) -> None:
        """A prefill replica published a finished request's KV
        (``KV_OFFER`` on its result stream — seq-ordered and
        epoch-fenced upstream, so duplicate and zombie offers never
        reach here). The sealed snapshot rides the message's snapshot
        channel, whose per-page digests were re-verified at receive —
        a STRIPPED (empty) offer therefore means wire corruption, and
        the record falls back to a full colocated recompute
        immediately. The ``fleet.handoff`` chaos site (ctx path = rid)
        drops, delays (in router steps) or corrupts the offer in
        flight; a corrupted-but-delivered payload is caught one hop
        later, by the decode replica's own digest gate at KV_PULL."""
        p = msg.payload()
        rid = p.get("rid", msg.rid)
        rec = self._records.get(rid)
        if rec is None or rec.finished:
            # late offer for a finished/shed record: nothing will ever
            # pull it — free the prefill server's held copy
            self._transport.send(Message.make(
                "KV_COMMIT", "router", f"replica:{rep.idx}",
                epoch=rep.epoch, rid=rid,
                payload={"rid": rid, "ack": rep.applied_seq}))
            return
        snap = msg.snaps[0] if msg.snaps else None
        fx = {"drop": False, "delay": 0}
        try:
            _fault.trip(
                "fleet.handoff", step=self._steps, path=rid,
                drop=lambda: fx.__setitem__("drop", True),
                delay=lambda steps: fx.__setitem__("delay", int(steps)),
                corrupt=(snap.corrupt if snap is not None
                         else lambda: None))
        except _fault.FaultInjected:
            fx["drop"] = True
        if fx["drop"]:
            self._handoff_fallback(rec, "offer_dropped")
            return
        if snap is None:
            # the wire's digest gate stripped a corrupt payload
            self.fleet_metrics.bump("handoff_corrupt")
            self._handoff_fallback(rec, "offer_corrupt")
            return
        if fx["delay"] > 0:
            self._handoff_delayed.append(
                (self._steps + fx["delay"], rep.idx, rid, snap))
            return
        self._store_offer(rep.idx, rid, snap)

    def _store_offer(self, src_idx: int, rid: str, snap) -> None:
        rec = self._records.get(rid)
        if rec is None or rec.finished or rec.handoff_fallback:
            return
        self._offers[rid] = (src_idx, snap)
        rec.handoff_src = src_idx
        rec.handoff_wait_since = self._steps   # restart: the pull phase
        self.fleet_metrics.bump("handoff_offers")
        self.fleet_metrics.bump("handoff_bytes", int(snap.nbytes))
        self._progress_flag = True
        self.tracer.instant("handoff_offer", track="fleet", rid=rid,
                            replica=src_idx, nbytes=int(snap.nbytes))

    def _handoff_fallback(self, rec: FleetRequest, why: str) -> None:
        """Degrade a handoff to a plain colocated recompute: the
        record re-enters the normal placement lane, charging a full
        prefill — slower, never wrong. (The prefill replica registered
        the prompt in its prefix index when the handoff finished, so a
        recompute landing back THERE is a warm cache hit.)"""
        if rec.finished or rec.handoff_fallback:
            return
        rec.handoff_fallback = True
        self._offers.pop(rec.rid, None)
        self.fleet_metrics.bump("handoff_recomputes")
        self._progress_flag = True
        self.tracer.instant("handoff_fallback", track="fleet",
                            rid=rec.rid, reason=why)

    def _commit_handoff(self, rec: FleetRequest) -> None:
        """Tell the prefill source its held KV copy is safe to free
        (idempotent under redelivery; at most once per record). The
        ROUTER keeps its own offer reference until the record finishes,
        so a decode-replica death after commit still re-pulls from the
        router-held snapshot rather than recomputing."""
        if rec.handoff_src is None or rec.handoff_committed:
            return
        rec.handoff_committed = True
        src = self._replicas[rec.handoff_src]
        if src.state == DEAD:
            return             # the life that held the copy is gone
        self.fleet_metrics.bump("handoff_commits")
        self._transport.send(Message.make(
            "KV_COMMIT", "router", f"replica:{src.idx}", epoch=src.epoch,
            rid=rec.rid, payload={"rid": rec.rid,
                                  "ack": src.applied_seq}))

    def _handoff_release(self, rec: FleetRequest) -> None:
        """Terminal cleanup: drop the router-held offer and free the
        source's held copy if the pull never landed."""
        self._offers.pop(rec.rid, None)
        self._commit_handoff(rec)

    def _handoff_sweep(self) -> None:
        """Disagg liveness: release chaos-delayed offers whose hold
        expired, then fall back to full recompute for any record whose
        offer can no longer arrive (prefill source DEAD before
        publishing) or has waited past ``handoff_timeout_steps``.
        The timeout sits strictly inside ``shed_patience``, so a
        wedged handoff degrades to a recompute long before the router
        would shed the request."""
        if self.placement != "disagg":
            return
        if self._handoff_delayed:
            due = [d for d in self._handoff_delayed
                   if d[0] <= self._steps]
            if due:
                self._handoff_delayed = [d for d in self._handoff_delayed
                                         if d[0] > self._steps]
                for _, src_idx, rid, snap in due:
                    self._store_offer(src_idx, rid, snap)
        for rec in self._pending:
            if (rec.finished or rec.handoff_src is None
                    or rec.handoff_fallback
                    or rec.rid in self._offers
                    or rec.rid in self._outstanding):
                continue
            src = self._replicas[rec.handoff_src]
            in_delay = any(d[2] == rec.rid for d in self._handoff_delayed)
            if src.state == DEAD and not in_delay:
                # unclaimed offer died with its source -> recompute
                self._handoff_fallback(rec, "src_dead")
            elif (self._steps - rec.handoff_wait_since
                    > self.handoff_timeout_steps):
                self.fleet_metrics.bump("handoff_timeouts")
                self._handoff_fallback(rec, "timeout")

    def _reroll_sweep(self) -> None:
        """Elastic role re-rolling: every ``reroll_interval`` router
        steps, compare prefill-side pressure (router queue of requests
        still owing a prefill + load on prefill-role replicas, per
        replica) against decode-side pressure (load + brownout rungs
        on decode-role replicas, per replica — the ladder's rung IS
        the ITL-pressure signal). A sustained imbalance —
        ``reroll_dwell`` consecutive readings leaning the same way —
        flips ONE IDLE replica from the calm side to the starved side,
        never the last member of a role; an extinct role is restored
        immediately. Only an idle replica flips (no live requests, no
        pinned submits), so a re-roll never migrates or disturbs
        in-flight work: "draining" a donor is simply the role filter
        in ``_dispatch`` no longer placing new work on it."""
        if (self.placement != "disagg" or self.reroll_interval <= 0
                or self._steps == 0
                or self._steps % self.reroll_interval):
            return
        alive = [r for r in self._replicas if r.state != DEAD]
        pre = [r for r in alive if r.role == "prefill"]
        dec = [r for r in alive if r.role == "decode"]
        if not alive:
            return
        if not pre and dec:
            self._reroll(dec, "prefill")   # restore the extinct role
            return
        if not dec and pre:
            self._reroll(pre, "decode")
            return
        owing = sum(1 for rec in self._pending
                    if not rec.finished and rec.handoff_src is None
                    and not rec.handoff_fallback)
        pre_p = (owing + sum(self._load(r) for r in pre)) / len(pre)
        dec_p = sum(self._load(r)
                    + int(r.gauges.get("brownout_level", 0))
                    for r in dec) / len(dec)
        if pre_p > 2.0 * dec_p + 1.0:
            self._reroll_pressure = max(1, self._reroll_pressure + 1)
        elif dec_p > 2.0 * pre_p + 1.0:
            self._reroll_pressure = min(-1, self._reroll_pressure - 1)
        else:
            self._reroll_pressure = 0
        if self._reroll_pressure >= self.reroll_dwell and len(dec) > 1:
            if self._reroll(dec, "prefill"):
                self._reroll_pressure = 0
        elif self._reroll_pressure <= -self.reroll_dwell and len(pre) > 1:
            if self._reroll(pre, "decode"):
                self._reroll_pressure = 0

    def _reroll(self, donors: list, new_role: str) -> bool:
        """Flip the least-loaded IDLE donor to ``new_role``; False if
        every donor still holds work (try again next interval)."""
        pinned = {e[0] for e in self._outstanding.values()}
        idle = [r for r in donors
                if not r.live_rids and self._load(r) == 0
                and r.idx not in pinned]
        if not idle:
            return False
        rep = min(idle, key=lambda r: r.idx)
        was = rep.role
        rep.role = new_role
        self.fleet_metrics.bump("rerolls")
        self.tracer.instant("reroll", track="fleet", replica=rep.idx,
                            role=new_role, was=was)
        return True

    # ------------------------------------------------------------------
    # exactly-once translation
    # ------------------------------------------------------------------

    def _translate(self, rep: _Replica, replica_events: list[dict],
                   out: list[dict]) -> None:
        """Engine events -> client events, deduping replayed positions.

        A token at position ``produced <= emitted`` is a replay of one
        the client already has: it is verified bitwise against the
        delivered stream (the determinism contract — a mismatch is a
        hard error, not a silent corruption) and suppressed. The first
        fresh position is delivered and ``emitted`` advances. Terminal
        classification events (token None) always deliver — they can
        never duplicate, because a finished record leaves the in-flight
        set and is never replayed. (Whole duplicated/reordered BATCHES
        never reach here — the per-replica seq stream already collapsed
        them; this dedup is the per-TOKEN one that makes failover
        replay invisible.)"""
        for ev in replica_events:
            rec = self._records.get(ev["rid"])
            if rec is None or rec.finished:
                continue  # not ours / already terminal (late drain echo)
            if ev.get("finished") and ev.get("finish_reason") == "handoff":
                # disagg phase boundary, NOT a terminal: the prefill
                # replica finished the prompt and exported its KV. The
                # record re-enters the router queue at its ORIGINAL
                # submit order to await the offer/pull; the client sees
                # nothing (its first token comes from the decode side).
                rep.live_rids.discard(rec.rid)
                if rec.replica == rep.idx:
                    rec.replica = None
                rec.produced = 0
                rec.handoff_src = rep.idx
                rec.handoff_wait_since = self._steps
                self.metrics.on_prefill_complete(rec.rid)
                self.fleet_metrics.bump("handoff_prefills")
                if rec not in self._pending:
                    keys = [r.submit_seq for r in self._pending]
                    self._pending.insert(
                        bisect.bisect_left(keys, rec.submit_seq), rec)
                self._progress_flag = True
                self.tracer.instant("handoff_prefill", track="fleet",
                                    rid=rec.rid, replica=rep.idx)
                continue
            token = ev.get("token")
            if token is not None:
                rec.produced += 1
                if rec.produced <= rec.emitted:
                    expected = rec.tokens[rec.produced - 1]
                    if token != expected:
                        raise RuntimeError(
                            f"replay divergence for {rec.rid!r} at "
                            f"position {rec.produced}: replica "
                            f"{rep.idx} produced {token}, client was "
                            f"delivered {expected} — the deterministic-"
                            f"replay contract is broken")
                    self.fleet_metrics.bump("replayed_tokens")
                    if not ev.get("finished"):
                        continue   # pure replay: suppress
                    # a finish can only ride the LAST token; if that
                    # position was already emitted the original replica
                    # died after computing it but before the router saw
                    # it — impossible by construction (step boundaries),
                    # guarded anyway:
                    token = None
                else:
                    rec.emitted += 1
                    rec.tokens.append(token)
                    self.metrics.on_token(rec.rid)
                    t0 = self._recovering.pop(rec.rid, None)
                    if t0 is not None:
                        # first FRESH token after a failover: close the
                        # time-to-first-recovered-token window
                        self.fleet_metrics.observe_recovery(
                            self.metrics.now() - t0)
            if ev.get("finished"):
                reason = ev.get("finish_reason")
                rec.finished = True
                rec.finish_reason = reason
                rep.live_rids.discard(rec.rid)
                self._recovering.pop(rec.rid, None)
                self._handoff_release(rec)
                self.metrics.on_finish(rec.rid, reason)
                if reason not in ("stop", "length"):
                    self.metrics.on_outcome(reason)
                self.tracer.instant("finish", track="fleet", rid=rec.rid,
                                    reason=reason or "",
                                    replica=rep.idx)
            if token is not None or ev.get("finished"):
                out.append({"rid": rec.rid, "token": token,
                            "finished": bool(ev.get("finished")),
                            "finish_reason": ev.get("finish_reason"),
                            "replica": rep.idx})

    def _finish_record(self, rec: FleetRequest, reason: str,
                       events: list[dict]) -> None:
        """Router-side terminal classification (shed / preempted /
        rejected): the client gets a typed outcome, never silence."""
        rec.finished = True
        rec.finish_reason = reason
        if rec.replica is not None:
            self._replicas[rec.replica].live_rids.discard(rec.rid)
        rec.replica = None
        self._handoff_release(rec)
        ev = {"rid": rec.rid, "token": None, "finished": True,
              "finish_reason": reason, "replica": None}
        if reason == "shed":
            self.fleet_metrics.bump("shed")
            self.metrics.on_shed(rec.tenant, rec.priority)
            # clients implement backoff off the event itself
            # (RESILIENCE.md "Overload playbook")
            ev["retry_after_s"] = self._retry_after_s()
        self.metrics.on_finish(rec.rid, reason)
        self.metrics.on_outcome(reason)
        events.append(ev)
        self.tracer.instant("finish", track="fleet", rid=rec.rid,
                            reason=reason)

    # ------------------------------------------------------------------
    # snapshot harvest (replicas with private stores)
    # ------------------------------------------------------------------

    def _snapshot_fetch(self) -> None:
        """Pull snapshot captures from replicas whose engine keeps a
        PRIVATE store (a shared store needs no wire — the common
        default, where this is inert). Every snapshot re-verifies its
        own digests at receive; corrupt ones are stripped by the
        transport and simply not harvested."""
        if (self._snapshot_store is None or not self._fetch_idx
                or self.snapshot_fetch_interval <= 0
                or self._steps % self.snapshot_fetch_interval != 0):
            return
        for i in self._fetch_idx:
            rep = self._replicas[i]
            if rep.state == DEAD:
                continue
            self._transport.send(Message.make(
                "SNAPSHOT_FETCH", "router", f"replica:{i}",
                epoch=rep.epoch,
                payload={"known": {}, "ack": rep.applied_seq}))

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def request(self, rid: str) -> FleetRequest:
        return self._records[rid]

    def replicas_live(self) -> int:
        return sum(1 for rep in self._replicas if rep.state != DEAD)

    def stats(self) -> dict:
        """Fleet-level stats: router counters, per-replica health, and
        the transport/membership telemetry (the shape
        ``observability.render_fleet_prometheus`` exports)."""
        return {
            "steps": self._steps,
            "placement": self.placement,
            "replicas": len(self._replicas),
            "replicas_live": self.replicas_live(),
            "replicas_ejected": sum(1 for r in self._replicas
                                    if r.state == DEAD),
            "queue_depth": len(self._pending),
            "handoff_offers_held": len(self._offers),
            "requests": len(self._records),
            "draining": self._draining,
            "fleet": self.fleet_metrics.summary(),
            "transport": self._transport.stats(),
            "heartbeat_rtt_p50_steps": percentile(self._hb_rtt, 50),
            "heartbeat_rtt_p99_steps": percentile(self._hb_rtt, 99),
            "replica_health": [self.health(i)
                               for i in range(len(self._replicas))],
        }

    @property
    def engines(self):
        return [rep.engine for rep in self._replicas]

    @property
    def transport(self):
        """The message fabric every router<->replica interaction
        crosses (LoopbackTransport unless injected)."""
        return self._transport

    @property
    def snapshot_store(self):
        """The shared bounded-replay snapshot store (None = every
        failover is a full replay)."""
        return self._snapshot_store
