"""Continuous-batching serving engine over the paged KV-cache pool.

The engine owns exactly TWO compiled programs for its lifetime:

- the 1-token decode step, always over the fixed ``[max_slots]`` slot
  axis with block tables, position offsets, the active mask and every
  per-request sampling parameter as ARRAY inputs — requests joining,
  finishing or being preempted change array *values*, never shapes, so
  ``decode_program_count()`` stays at 1 across arbitrary churn
  (asserted by tests/test_serving.py);
- the MIXED step, fixed shape ``[max_slots, chunk]``: each slot carries
  ``(start_pos, n_new)`` as the ``seq_lens``/``n_live`` array lanes and
  processes either a budget-sized PREFILL CHUNK (``forced`` lane set:
  its rows are teacher-forced prompt tokens) or its decode input plus
  up to k-1 speculative draft tokens — Orca's iteration-level batching
  with Sarathi-Serve's chunked prefill. One program serves prefill,
  decode+verify, and any mixture; the old O(log max_len) pow2
  suffix-bucket prefill family is gone.

Long prompts stream through the mixed step in chunks metered by the
per-step prefill token budget, so decode slots never stall behind a
prompt: a chunking slot occupies its lane with prompt rows while every
other slot keeps decoding in the same dispatch. All rows share the one
grouped GQA core and the paged scatter-at-write path (fp and int8 KV);
within a chunk, row j sits at pool position ``start_pos + j`` and
attends causally up to itself. Speculative verify is the degenerate
mixed step whose new tokens are draft rows instead of prompt rows: row
j is ACCEPTED iff it equals the row j-1 sample (Leviathan), rejected
rows are zeroed in-program, and a ``forced`` slot accepts all its rows
by construction. ``step_program_counts()`` reports both step shapes
and each stays pinned at 1 (O(1) programs, not O(prompt-length) or
O(accept-pattern)).

With ``prefix_cache=True`` (default) the pool indexes full pages by
chained content hash, shares them across requests via refcounts,
reuses partial pages copy-on-write, and LRU-evicts refcount-0 cached
pages when allocation would otherwise fail — see SERVING.md "Prefix
caching". Prefix registration commits on the FINAL chunk: a request
preempted mid-prompt registers nothing (and still drops its page
refs), so partial prompts can never serve future hits.

Determinism: greedy decode is argmax over logits that are bitwise equal
to ``LlamaForCausalLM.generate()``'s (shared attention core, masked
padding contributes exact zeros — see SERVING.md); sampled requests
draw token *n* with ``fold_in(PRNGKey(seed), n)`` so a preempted and
recomputed request reproduces its original stream regardless of slot
placement, chunk boundaries, or batch composition.

Robustness (SERVING.md "Serving failure modes"): every failure mode is
a classified per-request outcome or a typed :mod:`.errors` exception,
never an engine-wide hang — bounded-queue backpressure and
reject-at-add for impossible requests, per-request deadlines enforced
at step boundaries on the injectable metrics clock, a per-request
preemption cap, a non-finite logit sentinel that quarantines only the
offending slot (its pages are scrubbed back to zero so the pool's
masked-garbage-is-zero invariant survives reuse), zero-progress stall
detection (chunk progress counts as progress), and ``drain()`` for
graceful (SIGTERM) shutdown. The blocking per-step device sync runs
under ``watch("serving.step")`` and the fault sites ``serving.step`` /
``serving.prefill`` / ``serving.decode`` / ``serving.alloc`` make all
of it deterministically chaos-testable.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..distributed import fault as _fault
from ..observability.trace import NULL_TRACER
from .errors import (AdmissionShedError, EngineDrainingError, QueueFullError,
                     RequestTooLargeError, SchedulerStalledError)
from .kv_cache import KVCachePool
from .metrics import ServingMetrics
from .scheduler import FINISHED, Request, SamplingParams, Scheduler
from .snapshot import (RequestSnapshot, load_engine_snapshot,
                       save_engine_snapshot)

__all__ = ["ServingEngine", "BrownoutConfig"]

# consecutive zero-progress steps tolerated before SchedulerStalledError:
# a deterministic livelock (preempt-self treadmill, un-admittable queue
# head) repeats identically every step, while a transient injected alloc
# storm recovers as soon as its fault spec stops matching — so > 1, small
_STALL_PATIENCE = 3


@dataclass
class BrownoutConfig:
    """The brownout ladder's watermarks (SERVING.md "Overload control &
    tenant fairness"; RESILIENCE.md "Overload playbook").

    Queue-depth/wait-time watermarks drive staged degradation, one
    level per ``dwell_steps`` window (hysteresis — the ladder never
    flaps on a single-step spike): the engine escalates one level when
    ``queue_depth >= high_queue`` or the oldest queued request has
    waited ``high_wait_s`` (metrics clock), and de-escalates one level
    when ``queue_depth <= low_queue`` (and, if set, every queued wait
    is back under ``low_wait_s``). The levels are pure HOST-SIDE
    policy — no compiled shape moves, ``step_program_counts()`` stays
    ``{"decode": 1, "mixed": 1}`` across every transition:

    - level 1: the per-step prefill token budget shrinks to
      ``budget_frac`` of its configured value (admission + chunk
      metering slow down; decode latency recovers first);
    - level 2: speculation is suspended — the drafter is host-side, so
      skipping it just leaves the draft lanes empty;
    - level 3: the lowest-priority queued requests are shed
      (``finish_reason="shed"``, retryable) until the queue is back at
      the high watermark.
    """

    high_queue: int = 8
    low_queue: int = 2
    high_wait_s: float | None = None
    low_wait_s: float | None = None
    budget_frac: float = 0.5
    dwell_steps: int = 2

    def __post_init__(self):
        if self.low_queue > self.high_queue:
            raise ValueError("brownout low_queue must be <= high_queue "
                             f"(got {self.low_queue} > {self.high_queue})")
        if not 0.0 < self.budget_frac <= 1.0:
            raise ValueError("brownout budget_frac must be in (0, 1], "
                             f"got {self.budget_frac}")
        if self.dwell_steps < 1:
            raise ValueError("brownout dwell_steps must be >= 1, "
                             f"got {self.dwell_steps}")


class ServingEngine:
    def __init__(self, model, num_pages: int, page_size: int,
                 max_slots: int = 4, max_pages_per_slot: int | None = None,
                 prefill_token_budget: int = 2048, kv_dtype=None,
                 clock=None, max_queue_depth: int | None = None,
                 max_preemptions: int | None = None,
                 step_timeout_s: float | None = None,
                 drain_timeout_s: float | None = 30.0,
                 watchdog=None, prefix_cache: bool = True,
                 tracer=None, flight_recorder=None,
                 kv_quant: bool = False, speculative=None,
                 host_tier=None, chunked: bool = True,
                 prefill_chunk: int = 64, snapshot_store=None,
                 snapshot_interval: int = 16, tp: int = 1,
                 tp_devices=None, pp: int = 1,
                 pp_microbatch: bool = True,
                 fair_scheduling: bool = False,
                 tenant_weights=None, tenant_max_live: int | None = None,
                 tenant_max_queued_tokens: int | None = None,
                 shed_infeasible: bool = False, brownout=None,
                 lora=None):
        cfg = model.config
        self.model = model
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_pages_per_slot = (max_pages_per_slot
                                   if max_pages_per_slot is not None
                                   else (num_pages - 1))
        self.prefix_cache = prefix_cache
        # tensor parallelism (serving/parallel.py; SERVING.md
        # "Tensor-parallel serving"): tp=N spans this engine over N
        # devices (tp_devices, default the first N visible) — the KV
        # pool shards its kv-head dim, weights go column/row-parallel,
        # and each of the TWO step programs compiles as ONE shard_map
        # over the mp axis. tp=1 is exactly the single-device engine.
        # Un-shardable configs raise TPConfigError here, not a shape
        # crash inside the compiled step.
        # pipeline parallelism (same file; SERVING.md "Pipeline-parallel
        # serving"): pp=P stages the decoder over a leading pp mesh axis
        # — embed + the first L/pp layers on stage 0, lm_head + the last
        # on stage P-1 — with the KV pool stacked and carved per stage.
        # Each step is STILL one jit(shard_map) over the full pp×mp
        # mesh; stage handoff is a ppermute ring inside the program.
        # pp_microbatch splits the mixed step's chunk into pp waves so
        # stages overlap instead of idling (pp-1)/pp of the time.
        from .parallel import TPContext, validate_tp_config
        validate_tp_config(cfg, tp, pp)
        self.tp = int(tp)
        self.pp = int(pp)
        self._tp = (TPContext(model, tp, devices=tp_devices, pp=pp)
                    if tp > 1 or pp > 1 else None)
        self._pp_waves = self.pp if (self.pp > 1 and pp_microbatch) else 1
        # int8 KV mode: kv_quant=True, or kv_dtype="int8"/jnp.int8 — the
        # pool stores int8 codes + fp32 absmax scales, quantized at
        # scatter time and dequantized inside the one shared decode core
        # (quantization/serving.py; SERVING.md "Quantized KV & weights")
        if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
            kv_quant = True
        self.kv_quant = kv_quant
        # host-RAM spill tier (serving/tiering.py): True -> defaults, an
        # int -> byte budget, or a ready HostTier instance — share ONE
        # instance across homogeneous replicas and their spilled prefix
        # pages become fleet-wide warm cache (identical weights produce
        # bitwise-identical KV). Requires the prefix cache (spill keys
        # are its content hashes).
        self.pool = KVCachePool.from_config(
            cfg, num_pages, page_size,
            dtype=(jnp.bfloat16 if kv_quant or kv_dtype is None
                   else kv_dtype),
            cache_enabled=prefix_cache, quantized=kv_quant,
            host_tier=host_tier if prefix_cache else None,
            sharding=(self._tp.kv_shardings() if self._tp else None),
            tp_degree=self.tp, pp_degree=self.pp)
        # every (re-)admission must fit the slot's block table and the
        # rope table — admission_check guards the window up front
        self._ctx_pages = min(self.max_pages_per_slot,
                              self.pool.pages_for(
                                  cfg.max_position_embeddings))
        # SLO-aware overload control (SERVING.md "Overload control &
        # tenant fairness"): fair_scheduling turns on the weighted
        # virtual-token-counter queue across tenants (FCFS within a
        # tenant — streams stay bitwise identical to generate());
        # tenant_max_live / tenant_max_queued_tokens are per-tenant
        # admission quotas; shed_infeasible arms the deadline-
        # infeasibility gate; brownout takes a BrownoutConfig (or True
        # for defaults) to arm the staged-degradation ladder.
        self.scheduler = Scheduler(
            max_slots, prefill_token_budget,
            max_queue_depth=max_queue_depth,
            max_preemptions=max_preemptions,
            fair=fair_scheduling, tenant_weights=tenant_weights,
            tenant_max_live=tenant_max_live,
            tenant_max_queued_tokens=tenant_max_queued_tokens)
        # multi-tenant LoRA serving (serving/lora.py; SERVING.md
        # "Multi-tenant LoRA serving"): lora=True builds an AdapterPool
        # with defaults, a dict forwards kwargs, or pass a ready pool
        # (share one across colocated engines). Per-slot adapter
        # selection is an ARRAY lane of the two step programs — gather
        # by adapter-table index — so churn across thousands of
        # registered adapters never recompiles. tp>1 is gated here: the
        # adapter buffers are replicated host-built arrays and the TP
        # step's lane layout doesn't carry them yet.
        from .lora import AdapterPool
        if lora is True:
            lora = AdapterPool(cfg)
        elif isinstance(lora, dict):
            lora = AdapterPool(cfg, **lora)
        self.adapters: AdapterPool | None = lora or None
        if self.adapters is not None and (self.tp > 1 or self.pp > 1):
            from .errors import TPConfigError
            raise TPConfigError(
                "multi-tenant LoRA serving is single-shard for now: "
                "adapter buffers are not laid out for the TP/PP step "
                "programs (pass tp=1, pp=1 or lora=None)")
        self.scheduler.adapters = self.adapters
        if brownout is True:
            brownout = BrownoutConfig()
        elif brownout is False:
            brownout = None
        self._brownout: BrownoutConfig | None = brownout
        self._brownout_level = 0
        self._brownout_since = 0       # engine step of the last transition
        self._shed_infeasible = bool(shed_infeasible)
        # step-duration EMA on the metrics clock: the ONLY timing input
        # to the deterministic retry_after_s / infeasibility estimators
        self._step_dt_ema: float | None = None
        # speculative decoding (serving/speculative.py; SERVING.md
        # "Speculative decoding"): pass a SpeculativeConfig, an int k,
        # or True for defaults. Draft rows ride the mixed step's row
        # axis; the drafter runs host-side every step.
        from .speculative import SpeculativeConfig
        if speculative is True:
            speculative = SpeculativeConfig()
        elif speculative is False:
            speculative = None
        elif isinstance(speculative, int):
            speculative = SpeculativeConfig(k=int(speculative))
        self._spec: SpeculativeConfig | None = speculative
        self._drafter = speculative.make_drafter() if speculative else None
        self.scheduler.spec_k = speculative.k if speculative else 1
        # chunked prefill (SERVING.md "Chunked prefill & mixed steps"):
        # chunked=True streams admitted prompts through the mixed step
        # in prefill_chunk-sized bites interleaved with decode;
        # chunked=False runs the whole suffix through the same program
        # inside the admission loop (legacy whole-prompt pacing — the
        # A/B baseline arm). Either way the mixed step's row count is
        # ONE compile-time constant: max(prefill_chunk, spec_k).
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, "
                             f"got {prefill_chunk}")
        self.chunked = bool(chunked)
        self.prefill_chunk = int(prefill_chunk)
        self._chunk = max(self.prefill_chunk, self.scheduler.spec_k)
        if self._pp_waves > 1:
            # the microbatched mixed step splits its row axis into
            # pp equal waves — round the compile-time chunk up so the
            # wave width K/waves is integral (a few extra padded rows,
            # never a second program shape)
            self._chunk = -(-self._chunk // self._pp_waves) * self._pp_waves
        self.scheduler.chunked = self.chunked
        self.scheduler.pp_waves = self._pp_waves
        # crash-consistent snapshots (serving/snapshot.py; RESILIENCE.md
        # "Serving recovery playbook"): with a SnapshotStore attached,
        # every snapshot_interval steps the engine captures each live
        # request's resumable state — tokens so far plus its KV pages,
        # exported host-side with ONE batched device_get — so a fleet
        # router can bound failover replay to the tokens since the last
        # capture, and save_snapshot/restore give warm process restart.
        if snapshot_interval < 1:
            raise ValueError(f"snapshot_interval must be >= 1, "
                             f"got {snapshot_interval}")
        self.snapshot_store = snapshot_store
        self.snapshot_interval = int(snapshot_interval)
        # set this (or pass drain(snapshot_path=...)) to make SIGTERM
        # drains persist in-flight state instead of finishing it
        self.drain_snapshot_path: str | None = None
        self.metrics = ServingMetrics(clock)
        self.metrics.set_kv_quant(kv_quant)
        self.metrics.set_spec(speculative is not None)
        self.metrics.set_host_tier(self.pool.host_tier is not None)
        self.metrics.set_chunked(self.chunked)
        self.metrics.set_snapshots(snapshot_store is not None)
        self.metrics.set_tp(self.tp,
                            self.pool.kv_bytes_per_token_shard())
        self.metrics.set_pp(self.pp, self._pp_waves,
                            self.pipeline_bubble_frac())
        self.metrics.set_fair(fair_scheduling)
        self.metrics.set_brownout(self._brownout is not None)
        self.metrics.set_lora(self.adapters is not None)
        # observability (OBSERVABILITY.md): the tracer is shared with
        # the scheduler (request-lifecycle spans) and the pool
        # (eviction/COW/quarantine events); construct it on the same
        # clock as the metrics so spans and percentiles line up. The
        # flight recorder subscribes to the event stream and is
        # auto-dumped at terminal conditions (stall, nonfinite, drain,
        # watchdog timeout).
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.scheduler.tracer = self.tracer
        self.pool.tracer = self.tracer
        self.flight_recorder = flight_recorder
        if flight_recorder is not None:
            self.tracer.add_sink(flight_recorder.record)
        # retrace detection (tracing on): last-seen compiled-program
        # count PER STEP SHAPE ("decode", "mixed") — every shape is a
        # first-class program with its own sentinel
        self._step_traces: dict[str, int] = {}
        self._wd_hooked: set[int] = set()
        self.step_timeout_s = step_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self._watchdog = watchdog
        self._state = model.state_dict(include_non_persistable_buffer=True)
        if self._tp is not None:
            # one-time placement onto the mesh (column/row/vocab layout
            # from the creation-time weight specs); pp>1 first folds the
            # per-layer keys into [L, ...] stacks whose leading dim
            # shards on the pp axis
            if self.pp > 1:
                self._state = self._tp.stage_state(self._state)
            self._state = self._tp.shard_state(self._state)
        self._requests: dict[str, Request] = {}
        # disaggregated serving (SERVING.md "Disaggregated serving"):
        # finished-prefill KV exports waiting to be offered over the
        # fleet wire — filled by _handoff_finish at final-chunk
        # completion, drained by the EngineServer via take_handoffs()
        self._handoff_outbox: list[RequestSnapshot] = []
        self._rid_counter = itertools.count()
        self._steps = 0
        self._idle_steps = 0
        self._draining = False
        self._guard = None
        self.last_drain_events: list[dict] = []
        self._decode_step = self._build_decode_step()
        self._mixed_step = self._build_mixed_step()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def add_request(self, prompt, max_new_tokens: int,
                    sampling: SamplingParams | None = None,
                    eos_token_id: int | None = None,
                    rid: str | None = None,
                    deadline_s: float | None = None,
                    max_queue_wait_s: float | None = None,
                    tenant: int = 0, priority: int = 0,
                    prefill_only: bool = False,
                    adapter=None) -> str:
        """Admission control happens HERE, not in the scheduler loop:
        a request that can never run raises RequestTooLargeError, a full
        bounded queue raises QueueFullError, a draining engine raises
        EngineDrainingError, and an exhausted per-tenant quota or an
        infeasible deadline raises AdmissionShedError (with a computed
        ``retry_after_s``) — all typed (errors.py, each carrying a
        machine-readable ``retryable`` flag), all counted
        (metrics.counters). Callers holding a retryable rejection don't
        have to implement the retry themselves: a
        ``serving.fleet.FleetRouter`` front-end routes around full and
        draining replicas automatically (SERVING.md "Engine fleet &
        failover"). ``deadline_s`` / ``max_queue_wait_s`` are budgets
        from arrival on the metrics clock, enforced at step boundaries
        with ``finish_reason="timeout"``. ``tenant`` scopes the request
        under the fair scheduler and the admission quotas; ``priority``
        (larger = more important, default 0) orders brownout level-3
        shedding — neither changes the tokens a stream produces.
        ``prefill_only=True`` marks a disaggregated-serving handoff
        request (SERVING.md "Disaggregated serving"): the engine runs
        the prompt through its mixed-step chunks, then — instead of
        emitting the first token — exports the finished KV to the
        handoff outbox (:meth:`take_handoffs`) and finishes the request
        with reason ``"handoff"``; a decode-role replica emits every
        token of the stream. ``adapter`` names the LoRA adapter to
        decode with (a registered name, hex digest, digest bytes, or
        LoRAAdapter — resolved by the engine's AdapterPool; requires
        ``lora=...`` at construction): an unknown adapter is rejected
        HERE with AdapterUnavailableError, and the stream is bitwise
        identical to ``generate()`` with that adapter merged into the
        base weights."""
        if self._draining:
            raise EngineDrainingError(
                "engine is draining (preempted or shut down); retry on "
                "another replica (serving.fleet.FleetRouter skips "
                "draining replicas at placement time)")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        adapter_hex = ""
        if adapter is not None and adapter != "":
            from .lora import AdapterUnavailableError
            if self.adapters is None:
                raise AdapterUnavailableError(
                    "engine was built without lora=...; pass "
                    "lora=True (or an AdapterPool) to serve adapters")
            adapter_hex = self.adapters.resolve(adapter).hex()
        try:
            self.admission_check(len(prompt), max_new_tokens)
        except RequestTooLargeError:
            self.metrics.on_reject("too_large")
            raise
        rid = rid if rid is not None else f"req-{next(self._rid_counter)}"
        old = self._requests.get(rid)
        if old is not None:
            if not old.done:
                raise ValueError(f"duplicate request id {rid!r}")
            # a FINISHED record is safe to supersede — the disagg
            # router legitimately re-admits a rid after its prefill
            # phase finished here with reason "handoff" (fallback
            # recompute landing back on the warm prefill replica)
            del self._requests[rid]
        # chaos site: an injected admission fault models a crash in the
        # overload-control path itself — typed, keyed by rid
        _fault.trip("serving.admission", step=self._steps, path=rid)
        self._check_overload_gates(len(prompt), max_new_tokens,
                                   int(tenant), int(priority), deadline_s)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      sampling=sampling or SamplingParams(),
                      eos_token_id=eos_token_id,
                      deadline_s=deadline_s,
                      max_queue_wait_s=max_queue_wait_s,
                      arrival_t=self.metrics.now(),
                      tenant=int(tenant), priority=int(priority),
                      handoff=bool(prefill_only), adapter=adapter_hex)
        try:
            self.scheduler.add(req, self.pool)
        except QueueFullError:
            self.metrics.on_reject("queue_full")
            raise
        except RequestTooLargeError:
            self.metrics.on_reject("too_large")
            raise
        self._requests[rid] = req
        self.metrics.on_arrival(rid, tenant=int(tenant),
                                priority=int(priority))
        return rid

    def register_adapter(self, adapter) -> str:
        """Register a :class:`serving.lora.LoRAAdapter` with this
        engine's AdapterPool and return its content digest (hex) — the
        handle ``add_request(adapter=...)``, fleet ``submit`` and
        snapshots carry. Registration spills the payload to the pool's
        host tier; device residency is paid lazily at first admission."""
        from .lora import AdapterUnavailableError
        if self.adapters is None:
            raise AdapterUnavailableError(
                "engine was built without lora=...; pass lora=True "
                "(or an AdapterPool) to register adapters")
        return self.adapters.register(adapter)

    def admission_check(self, prompt_len: int, max_new_tokens: int) -> None:
        """Raise RequestTooLargeError if a request of this geometry can
        NEVER run here, regardless of current load. Pure — no counters,
        no state: ``add_request`` wraps it with the reject accounting,
        and ``serving.fleet.FleetRouter`` calls it at submit time so an
        impossible request is refused fleet-wide before it occupies
        queue space anywhere (homogeneous replicas all reject it
        identically, hence ``RequestTooLargeError.retryable = False``)."""
        total = prompt_len + max_new_tokens
        need = self.pool.pages_for(total)
        if need > self.max_pages_per_slot:
            raise RequestTooLargeError(
                f"request needs {need} pages "
                f"(max_pages_per_slot={self.max_pages_per_slot})")
        # any (re-)admission must fit the context window: the longest
        # possible recompute is prompt + max_new - 1 tokens
        ctx = self._ctx_pages * self.page_size
        if total - 1 > ctx:
            raise RequestTooLargeError(
                f"request context ({total} tokens) exceeds the context "
                f"window of {ctx} tokens ({self._ctx_pages} pages; "
                f"bounded by max_position_embeddings and "
                f"max_pages_per_slot)")

    def _check_overload_gates(self, prompt_len: int, max_new_tokens: int,
                              tenant: int, priority: int,
                              deadline_s: float | None) -> None:
        """Load-DEPENDENT admission gates, layered over the
        load-independent geometry check in :meth:`admission_check`:
        the per-tenant queued-token quota, then the opt-in
        deadline-infeasibility shed. Both raise
        :class:`AdmissionShedError` (retryable, with a deterministic
        ``retry_after_s`` drain estimate) BEFORE the request holds any
        queue slot or pool page — shedding at the door is what keeps a
        doomed request from evicting feasible work later."""
        need = prompt_len + max_new_tokens
        cap = self.scheduler.tenant_max_queued_tokens
        if cap is not None:
            held = self.scheduler.queued_tokens(tenant)
            if held + need > cap:
                retry = self._drain_eta_s(held)
                self.metrics.on_reject("quota")
                self.metrics.on_shed(tenant, priority)
                self.tracer.instant("admission_shed", kind="tenant_quota",
                                    tenant=tenant)
                raise AdmissionShedError(
                    f"tenant {tenant} queued-token quota exhausted "
                    f"({held} held + {need} requested > cap {cap}); "
                    f"retry after ~{retry:.3f}s",
                    retry_after_s=retry, kind="tenant_quota",
                    tenant=tenant)
        if self._shed_infeasible and deadline_s is not None:
            eta = self._completion_eta_s(prompt_len, max_new_tokens)
            if eta is not None and eta > deadline_s:
                retry = self._drain_eta_s(self._queued_service_tokens())
                self.metrics.on_reject("infeasible")
                self.metrics.on_shed(tenant, priority)
                self.tracer.instant("admission_shed",
                                    kind="deadline_infeasible",
                                    tenant=tenant)
                raise AdmissionShedError(
                    f"deadline {deadline_s:.3f}s is infeasible: estimated "
                    f"completion ~{eta:.3f}s behind the current backlog; "
                    f"retry after ~{retry:.3f}s",
                    retry_after_s=retry, kind="deadline_infeasible",
                    tenant=tenant)

    def _effective_prefill_budget(self) -> int:
        """The per-step prefill/chunk token budget AFTER brownout:
        level >= 1 shrinks it to ``budget_frac`` of the configured
        value — a host-side scalar, never a compiled shape."""
        base = self.scheduler.prefill_token_budget
        if self._brownout is not None and self._brownout_level >= 1:
            base = max(1, int(base * self._brownout.budget_frac))
        return base

    def _token_capacity_per_step(self) -> int:
        """Service tokens one step can retire: the (brownout-effective)
        prefill budget plus one decode token per slot."""
        return max(1, self._effective_prefill_budget() + self.max_slots)

    def _queued_service_tokens(self) -> int:
        """Total service tokens (recompute + decode budget) held by the
        waiting queue — the backlog the drain estimators divide down."""
        return sum(max(r.recompute_len, 1) + r.max_new_tokens
                   for r in self.scheduler.waiting)

    def _drain_eta_s(self, tokens: int) -> float:
        """Deterministic drain-rate estimate behind every
        ``retry_after_s`` hint: queued service tokens over per-step
        token capacity, scaled by the step-duration EMA on the metrics
        clock. 0.0 before the first timed step — an honest "no data
        yet", never a fabricated constant."""
        if self._step_dt_ema is None or self._step_dt_ema <= 0.0:
            return 0.0
        return (tokens / self._token_capacity_per_step()
                * self._step_dt_ema)

    def _completion_eta_s(self, prompt_len: int,
                          max_new_tokens: int) -> float | None:
        """Estimated queue wait + prefill + decode for a NEW arrival:
        the backlog drains first, then its own prefill streams at the
        effective chunk budget, then ~one decoded token per step. None
        before the first timed step (no EMA -> no estimate -> the
        infeasibility gate never sheds on a cold engine)."""
        if self._step_dt_ema is None or self._step_dt_ema <= 0.0:
            return None
        queue_steps = (self._queued_service_tokens()
                       / self._token_capacity_per_step())
        own_steps = (prompt_len / self._effective_prefill_budget()
                     + max_new_tokens)
        return (queue_steps + own_steps) * self._step_dt_ema

    def step(self) -> list[dict]:
        """One scheduling iteration: expire deadlines, admit newly
        runnable requests (chunked: map pages only; unchunked: run the
        whole prefill inline), guarantee decode pages (preempting if
        needed), then ONE batched dispatch over the running slots —
        prefill chunks and decode/verify rows share the mixed program;
        a pure-decode step keeps the cheap ``[max_slots]`` program.
        Returns this step's token/finish events. A zero-progress step
        with work still pending raises SchedulerStalledError instead of
        letting ``run_to_completion`` busy-loop."""
        if not self.scheduler.has_work():
            return []
        # key this step's serving.alloc fault draws by the ENGINE step
        # (not the process-global training cursor) so probabilistic
        # storms vary over the engine's lifetime deterministically
        self.pool.fault_step = self._steps
        _fault.trip("serving.step", step=self._steps)
        tr = self.tracer
        t_step0 = self.metrics.now()
        events: list[dict] = []
        with tr.span("deadline_sweep", queue=self.scheduler.queue_depth):
            self._expire_deadlines(events)
        if self._draining:
            self._flush_waiting(events)
        elif self._brownout is not None:
            # one hysteresis tick of the brownout ladder BEFORE the
            # budget is computed, so a fresh transition takes effect
            # this very step (level-3 queue sheds land in `events`)
            with tr.span("brownout", level=self._brownout_level):
                self._update_brownout(events)
        # the verify/chunk rows and any admission prefill share ONE
        # per-step token-work bound: the (brownout-effective) prefill
        # budget, minus the (spec_k - 1) verify rows each decoding slot
        # may score
        budget = (self._effective_prefill_budget()
                  - self.scheduler.verify_token_reserve())
        if not self._draining:
            # admit one request at a time. Unchunked: run its prefill
            # immediately so the NEXT admission's prefix lookup sees the
            # pages this prefill just registered (a same-step burst
            # sharing a system prompt prefills the common prefix once).
            # Chunked: just map pages — the suffix streams through the
            # mixed step below, and registration commits on the final
            # chunk.
            first = True
            while True:
                with tr.span("admission"):
                    batch = self.scheduler.admit(self.pool, limit=1,
                                                 budget=budget, first=first)
                if not batch:
                    break
                req = batch[0]
                first = False
                self.metrics.on_admit(req.rid)
                self.metrics.on_prefill(req.cached_len, req.prefill_target,
                                        req.restored_len)
                if self.chunked:
                    budget -= self.pool.restore_charge_tokens(
                        req.restored_len)
                    if not req.prefilling:
                        # recompute fully served from the prefix cache:
                        # the pages already hold the context bit-for-bit
                        # — no chunks owed, the stored last token drives
                        # the next decode row
                        tr.instant("prefill_cached", track=req.rid,
                                   cached=req.cached_len)
                else:
                    budget -= (req.context_len - req.cached_len
                               + self.pool.restore_charge_tokens(
                                   req.restored_len)
                               + (self.scheduler.spec_k - 1))
                    with tr.span("prefill_dispatch", rid=req.rid):
                        self._run_prefill(req, events)
        # adapter admit failures (lost/corrupt payload at acquire —
        # serving.lora_fetch chaos or a dropped host tier): terminal,
        # typed, never silently served base weights
        for req in self.scheduler.admit_failures:
            self._finish_abnormal(req, "adapter_unavailable", events)
        self.scheduler.admit_failures.clear()
        # drafts are proposed BEFORE the page guarantee so
        # ensure_decode_pages covers the speculative writes too
        if self._spec is not None and self.scheduler.running:
            if self._brownout_level >= 2:
                # brownout level 2: suspend speculation — the drafter is
                # pure host code, so "off" is just empty draft lanes;
                # the mixed program's row count never moves
                for req in self.scheduler.running.values():
                    req.draft_tokens = []
            else:
                self._propose_drafts()
        with tr.span("ensure_pages"):
            preempted = self.scheduler.ensure_decode_pages(self.pool)
        for victim in preempted:
            self.metrics.on_preemption()
            if victim.state == FINISHED:  # hit the max_preemptions cap
                self.metrics.on_outcome("preempted_limit")
                self.metrics.on_finish(victim.rid, "preempted_limit")
                self._trace_finish(victim, "preempted_limit")
                events.append({"rid": victim.rid, "token": None,
                               "finished": True,
                               "finish_reason": "preempted_limit"})
        chunk_tokens = 0
        if self.scheduler.running:
            chunk_tokens = self._run_batch(events, max(budget, 0))
        self.metrics.on_prefix_counters(self.pool.counters)
        if self.pool.host_tier is not None:
            self.metrics.on_tier_stats(self.pool.host_tier.stats())
        if self.adapters is not None:
            self.metrics.on_lora_stats(self.adapters.stats())
        self.metrics.on_step(self.scheduler.queue_depth,
                             self.pool.utilization())
        self._steps += 1
        if (self.snapshot_store is not None
                and self._steps % self.snapshot_interval == 0):
            # capture at the step boundary: pages hold exactly
            # context_len tokens, positions beyond are zeros (rejected
            # rows were zeroed in-program) or unreached stale content —
            # the tail page is sanitized host-side at export
            with tr.span("snapshot_capture"):
                self._capture_snapshots()
        if events or chunk_tokens or not self.scheduler.waiting:
            # chunk tokens are progress even before any emission: a
            # long prompt legitimately spends several steps mid-prefill
            self._idle_steps = 0
        else:
            # work is pending but nothing was admitted, decoded or
            # finished (the preempt-self livelock / un-admittable-head
            # shape). A deterministic livelock repeats this identically
            # every step — after _STALL_PATIENCE of them, surface the
            # evidence instead of letting run_to_completion busy-loop.
            self._idle_steps += 1
            if self._idle_steps >= _STALL_PATIENCE:
                head = self.scheduler.waiting[0]
                snapshot = {
                    "step": self._steps,
                    "idle_steps": self._idle_steps,
                    "queue_depth": self.scheduler.queue_depth,
                    "head_rid": head.rid,
                    "head_needs_pages": self.pool.pages_for(
                        max(head.recompute_len, 1)),
                    "free_pages": self.pool.num_free,
                    "capacity": self.pool.capacity,
                    "running": len(self.scheduler.running),
                }
                tr.instant("stall", idle_steps=self._idle_steps,
                           queue=self.scheduler.queue_depth)
                dump = self._dump_flight("scheduler_stalled", snapshot)
                if dump is not None:
                    snapshot["flight_recorder"] = dump
                raise SchedulerStalledError(
                    f"{snapshot['idle_steps']} zero-progress steps with "
                    f"{snapshot['queue_depth']} request(s) pending: head "
                    f"{head.rid!r} needs {snapshot['head_needs_pages']} "
                    f"pages, {snapshot['free_pages']} free "
                    f"(capacity {snapshot['capacity']})", snapshot)
        # feed the step-duration EMA (metrics clock) the retry_after_s /
        # infeasibility estimators divide by; a zero-dt step (virtual
        # clock not advanced) contributes nothing
        dt = self.metrics.now() - t_step0
        if dt > 0.0:
            self._step_dt_ema = (dt if self._step_dt_ema is None
                                 else 0.8 * self._step_dt_ema + 0.2 * dt)
        return events

    def stream(self):
        """Drive the engine to completion, yielding events as they are
        produced: ``{"rid", "token", "finished", "finish_reason"}``
        (abnormal finishes — timeout/nonfinite/preempted_limit/drain —
        carry ``token=None``). If a preemption guard is attached and
        trips (SIGTERM), the engine drains and the drain's terminal
        events are yielded before returning."""
        while self.scheduler.has_work():
            if self._preemption_pending():
                self.drain(timeout_s=self.drain_timeout_s)
                yield from self.last_drain_events
                return
            yield from self.step()

    def run_to_completion(self, max_steps: int | None = None) -> dict:
        """Drain the queue; returns {rid: generated token list}. On a
        tripped preemption guard the engine drains gracefully and every
        unfinished request ends with ``finish_reason="preempted"``."""
        steps = 0
        while self.scheduler.has_work():
            if self._preemption_pending():
                self.drain(timeout_s=self.drain_timeout_s)
                break
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(f"engine did not drain in {steps} steps")
        return {rid: list(r.tokens) for rid, r in self._requests.items()}

    def drain(self, timeout_s: float | None = None,
              snapshot_path: str | None = None) -> dict:
        """Graceful shutdown: stop admission, evict the waiting queue as
        ``finish_reason="preempted"`` ("retry elsewhere" — nothing was
        computed for them), let the running slots decode to their own
        finish until ``timeout_s`` (metrics clock) runs out, then evict
        the stragglers as preempted too. Returns the per-request outcome
        report {rid: {finish_reason, tokens, retriable}}; the terminal
        events produced during the drain are kept in
        ``last_drain_events``. Idempotent; after a drain,
        ``add_request`` raises EngineDrainingError.

        With ``snapshot_path`` (or ``drain_snapshot_path`` set), the
        drain takes the FAST path instead of decoding stragglers to
        completion: persist every in-flight request's resumable state
        with :meth:`save_snapshot`, then evict them all as retriable
        ``preempted`` outcomes. A warm restart
        (``ServingEngine.restore(path)``) continues every stream
        bitwise — the SIGTERM alternative when finishing all requests
        would blow the termination grace period."""
        events: list[dict] = []
        if snapshot_path is None:
            snapshot_path = self.drain_snapshot_path
        if snapshot_path is not None and not self._draining:
            self.save_snapshot(snapshot_path)
            self._draining = True
            self._flush_waiting(events)
            for req in list(self.scheduler.running.values()):
                self._finish_abnormal(req, "preempted", events)
            self.last_drain_events = events
            report = {rid: {"finish_reason": r.finish_reason,
                            "tokens": list(r.tokens),
                            "retriable": r.finish_reason == "preempted"}
                      for rid, r in self._requests.items()}
            self._dump_flight("drain", {
                "snapshot_path": snapshot_path,
                "outcomes": {rid: o["finish_reason"]
                             for rid, o in report.items()}})
            return report
        self._draining = True
        t0 = self.metrics.now()
        self._flush_waiting(events)
        while self.scheduler.running:
            if (timeout_s is not None
                    and self.metrics.now() - t0 >= timeout_s):
                for req in list(self.scheduler.running.values()):
                    self._finish_abnormal(req, "preempted", events)
                break
            events.extend(self.step())
        # the last step may have preempted a straggler back to waiting
        # AFTER that step's own flush — classify it before reporting
        self._flush_waiting(events)
        self.last_drain_events = events
        report = {rid: {"finish_reason": r.finish_reason,
                        "tokens": list(r.tokens),
                        "retriable": r.finish_reason == "preempted"}
                  for rid, r in self._requests.items()}
        self._dump_flight("drain", {
            "outcomes": {rid: o["finish_reason"]
                         for rid, o in report.items()}})
        return report

    # ------------------------------------------------------------------
    # crash-consistent snapshots (serving/snapshot.py)
    # ------------------------------------------------------------------

    def save_snapshot(self, path: str) -> str:
        """Durable warm-restart snapshot: capture every live request's
        resumable state NOW and persist it through the checkpoint
        commit protocol (stage into ``<path>.tmp``, ``COMMIT`` marker,
        rename — RESILIENCE.md). A crash mid-save leaves a torn staging
        dir that :meth:`restore` rejects; the previous committed
        snapshot at ``path`` is replaced only by the atomic rename."""
        snaps = self._capture_requests()
        # "tp"/"pp" are informational: payloads are full logical pages
        # (the capture device_get gathers shards, and the stacked pp
        # pool emits the same per-layer payload order), so a tp=2 or
        # pp=2 snapshot restores into a tp=1 engine and vice versa
        save_engine_snapshot(path, snaps, meta={
            "steps": self._steps, "kv_quant": self.kv_quant,
            "page_size": self.page_size, "tp": self.tp, "pp": self.pp})
        self.metrics.counters["snapshot_saves"] += 1
        self.tracer.instant("snapshot_save", requests=len(snaps),
                            step=self._steps)
        return path

    def restore(self, path: str) -> list[str]:
        """Warm restart: load a committed on-disk snapshot into this
        (fresh) engine and re-admit every request in its original
        arrival order, seeded with the tokens it had already generated
        — the streams continue bitwise from where the dead process
        stopped (determinism: seed + token index reproduce every
        sample; the injected KV only saves recompute). Raises
        :class:`CheckpointCorruptionError` on a torn or unverifiable
        snapshot dir. Returns the restored rids."""
        snaps, _meta = load_engine_snapshot(path)
        return [self.restore_request(s) for s in snaps]

    def restore_request(self, snap: RequestSnapshot,
                        tenant: int = 0, priority: int = 0) -> str:
        """Re-admit one snapshotted request (fleet failover and warm
        restart both land here). The snapshot's KV payloads — if any,
        and if their digests still verify — are injected into the pool
        as refcount-0 cached pages, so the ordinary admission prefix
        match maps them and the request resumes with zero (or near-
        zero) recompute; any verification failure just downgrades to
        the full-recompute path, which is bitwise-identical anyway.
        ``tenant``/``priority`` are re-attached by the caller (the
        snapshot format is unchanged; the fleet router carries them on
        its records) and the SURVIVOR's per-tenant quotas apply: a
        failed-over request that would bust the survivor's quota is
        refused with AdmissionShedError and stays queued at the router
        for the next placement attempt."""
        if self._draining:
            raise EngineDrainingError(
                "engine is draining; restore on another replica")
        rid = snap.rid
        old = self._requests.get(rid)
        if old is not None:
            if not old.done:
                raise ValueError(f"duplicate request id {rid!r}")
            # superseding a finished life of the same rid (see
            # add_request) — a KV_PULL may land on the very replica
            # that ran the prefill phase when the decode role starves
            del self._requests[rid]
        self.admission_check(len(snap.prompt), snap.max_new_tokens)
        self._check_overload_gates(len(snap.prompt), snap.max_new_tokens,
                                   int(tenant), int(priority), None)
        # the payload is usable only in the pool's own storage format
        # (int8 codes+scales vs fp pages have different bytes) and page
        # geometry — a mismatch is a recompute, never a reinterpret
        inject = bool(snap.payloads) and (
            snap.kv_tag == self.pool._tier_tag
            and snap.page_size == self.page_size)
        if inject:
            try:
                _fault.trip("serving.snapshot_restore", step=self._steps,
                            path=rid, poison=snap.corrupt)
            except _fault.FaultInjected:
                inject = False
                self.metrics.counters["snapshot_restore_failed"] += 1
            if inject and not snap.verify():
                # bit rot (or the poison action above) since capture:
                # the digest re-verify catches it HERE, before any byte
                # reaches the pool — fall back to recompute
                inject = False
                self.metrics.counters["snapshot_restore_corrupt"] += 1
        # multi-tenant LoRA: an adapter-bound snapshot restores only on
        # an engine that can actually serve that adapter — unknown here
        # means typed refusal (the router retries elsewhere), never a
        # silent base-model resume. Its injected KV lands under the
        # adapter's prefix-cache namespace, so the re-admission match
        # finds it and a foreign adapter's identical prompt cannot.
        if snap.adapter:
            from .lora import AdapterUnavailableError
            if self.adapters is None:
                raise AdapterUnavailableError(
                    f"snapshot {rid!r} is bound to adapter "
                    f"{snap.adapter[:12]}... but this engine was built "
                    f"without lora=...")
            self.adapters.resolve(snap.adapter)
        if inject:
            self.pool.inject_prefix(snap.seq(), snap.payloads,
                                    namespace=bytes.fromhex(snap.adapter)
                                    if snap.adapter else b"")
        req = Request(rid=rid, prompt=list(snap.prompt),
                      max_new_tokens=snap.max_new_tokens,
                      sampling=SamplingParams(
                          temperature=snap.temperature, top_p=snap.top_p,
                          do_sample=snap.do_sample, seed=snap.seed),
                      eos_token_id=snap.eos_token_id,
                      arrival_t=self.metrics.now(),
                      tenant=int(tenant), priority=int(priority),
                      adapter=snap.adapter)
        req.tokens = list(snap.tokens)
        try:
            self.scheduler.add(req, self.pool)
        except QueueFullError:
            self.metrics.on_reject("queue_full")
            raise
        self._requests[rid] = req
        self.metrics.on_arrival(rid, tenant=int(tenant),
                                priority=int(priority))
        self.metrics.counters["snapshot_restores"] += 1
        self.metrics.counters["snapshot_restored_tokens"] += len(snap.tokens)
        self.tracer.instant("snapshot_restore", track=rid,
                            tokens=len(snap.tokens), injected=int(inject))
        return rid

    def audit_pool(self, check_device: bool = True) -> dict:
        """Run the pool's invariant audit (``KVCachePool.audit``)
        against the scheduler's live block tables — the test-teardown /
        chaos-suite hook proving the engine left the pool consistent."""
        tables = [list(r.pages)
                  for r in self.scheduler.running.values() if r.pages]
        return self.pool.audit(block_tables=tables,
                               check_device=check_device)

    def _capture_requests(self) -> list[RequestSnapshot]:
        """Sealed snapshot of every live request, via ONE batched
        ``export_pages`` device_get across all their pages — host-side,
        outside every compiled program, so ``step_program_counts()``
        never moves. A request whose cache holds nothing yet (still
        queued, or admitted at context 0) gets a meta-only snapshot:
        replay still skips re-emitting its already-delivered tokens."""
        ps = self.page_size
        spans: list[tuple[Request, int]] = []
        flat: list[int] = []
        for r in self.scheduler.live_requests():
            n = 0
            if r.pages and r.context_len > 0:
                n = min(self.pool.pages_for(r.context_len), len(r.pages))
            spans.append((r, n))
            flat.extend(r.pages[:n])
        exported = self.pool.export_pages(flat)
        snaps: list[RequestSnapshot] = []
        i = 0
        for r, n in spans:
            payloads = exported[i:i + n]
            i += n
            q = r.context_len % ps
            if n and q and n == self.pool.pages_for(r.context_len):
                # the tail page holds q valid rows; rows beyond may be
                # stale from allocation — zero them host-side so the
                # payload matches the spill invariant (zeros beyond the
                # partial length) and the digest is deterministic
                tail = payloads[-1]
                for k, a in enumerate(tail):
                    a = np.array(a)
                    a[q:] = 0
                    tail[k] = a
            snaps.append(RequestSnapshot(
                rid=r.rid, prompt=list(r.prompt),
                max_new_tokens=r.max_new_tokens,
                eos_token_id=r.eos_token_id,
                temperature=r.sampling.temperature,
                top_p=r.sampling.top_p,
                do_sample=r.sampling.do_sample,
                seed=r.sampling.seed, arrival_seq=r.arrival_seq,
                tokens=list(r.tokens), context_len=int(r.context_len),
                step=self._steps, kv_tag=self.pool._tier_tag,
                page_size=ps, payloads=payloads,
                adapter=r.adapter).seal())
        return snaps

    def _capture_snapshots(self) -> None:
        """Periodic in-memory capture into the attached SnapshotStore
        (the fleet's bounded-replay source). Put-then-trip: the
        ``serving.snapshot`` fault site's ``poison`` action corrupts
        the JUST-stored snapshot (the restore-side digest re-verify
        must catch it); ``raise`` drops the capture — the previous
        snapshot, or full replay, covers the request."""
        store = self.snapshot_store
        snaps = self._capture_requests()
        if not snaps:
            return
        tr = self.tracer
        for snap in snaps:
            store.put(snap.rid, snap)
            try:
                _fault.trip("serving.snapshot", step=self._steps,
                            path=snap.rid,
                            poison=lambda rid=snap.rid: store.corrupt(rid))
            except _fault.FaultInjected:
                store.drop(snap.rid)
                store.counters["snapshot_failed"] += 1
                continue
            if tr.enabled:
                tr.instant("snapshot", track=snap.rid,
                           tokens=len(snap.tokens),
                           pages=len(snap.payloads))
        store.counters["snapshots_captured"] += 1
        self.metrics.on_snapshot_stats(store.stats())

    # ---- disaggregated prefill/decode serving (SERVING.md
    # "Disaggregated serving") ----

    def take_handoffs(self) -> list[RequestSnapshot]:
        """Drain the handoff outbox: sealed KV exports of prefill-only
        requests whose final chunk completed since the last call. The
        fleet's EngineServer streams each one to the router as an
        epoch-stamped ``KV_OFFER``; a decode-role replica then lands it
        via :meth:`restore_request` (``inject_prefix``)."""
        out, self._handoff_outbox = self._handoff_outbox, []
        return out

    def _capture_handoff(self, req: Request) -> RequestSnapshot:
        """Sealed snapshot of ONE request's finished prompt KV — the
        same HostTier payload format + per-page blake2b digests as
        :meth:`_capture_requests`, exported with one batched
        ``device_get`` outside both compiled programs. Captured at
        final-chunk completion, so ``tokens`` is empty and
        ``context_len`` is the full prompt length: the decode side
        re-admits it as a fresh request whose injected prefix matches
        ``n_valid - 1`` tokens and recomputes exactly one suffix row —
        the row whose sample is the (bitwise-identical) first token."""
        ps = self.page_size
        n = 0
        if req.pages and req.context_len > 0:
            n = min(self.pool.pages_for(req.context_len), len(req.pages))
        payloads = self.pool.export_pages(list(req.pages[:n]))
        q = req.context_len % ps
        if n and q and n == self.pool.pages_for(req.context_len):
            # zero the tail page's stale rows host-side (the spill
            # invariant: zeros beyond the partial length) so the digest
            # is deterministic — same rule as _capture_requests
            tail = payloads[-1]
            for k, a in enumerate(tail):
                a = np.array(a)
                a[q:] = 0
                tail[k] = a
        return RequestSnapshot(
            rid=req.rid, prompt=list(req.prompt),
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id,
            temperature=req.sampling.temperature,
            top_p=req.sampling.top_p,
            do_sample=req.sampling.do_sample,
            seed=req.sampling.seed, arrival_seq=req.arrival_seq,
            tokens=list(req.tokens), context_len=int(req.context_len),
            step=self._steps, kv_tag=self.pool._tier_tag,
            page_size=ps, payloads=payloads,
            adapter=req.adapter).seal()

    def _handoff_finish(self, req: Request, events: list[dict]) -> None:
        """Final-chunk completion of a prefill-only request: export its
        KV to the handoff outbox INSTEAD of emitting the first token,
        then finish it locally with reason ``"handoff"`` (the router
        treats that as a phase transition, not a terminal event — the
        client stream starts on the decode replica). Capture happens
        BEFORE the scheduler releases the pages; the release itself
        registers the prompt in the local prefix index, so a fallback
        recompute on this replica would still be a full cache hit."""
        snap = self._capture_handoff(req)
        self._handoff_outbox.append(snap)
        self.metrics.counters["handoff_exports"] += 1
        self.metrics.on_prefill_complete(req.rid)
        self.scheduler.finish(req, self.pool, "handoff")
        self.metrics.on_finish(req.rid, "handoff")
        self._trace_finish(req, "handoff")
        if self.snapshot_store is not None:
            self.snapshot_store.drop(req.rid)
        events.append({"rid": req.rid, "token": None, "finished": True,
                       "finish_reason": "handoff"})

    def attach_preemption_guard(self, guard=None):
        """Wire SIGTERM to a graceful drain: with a guard attached,
        ``stream`` / ``run_to_completion`` notice ``guard.preempted``
        at the next step boundary and call ``drain`` — a preempted
        server returns structured retry-elsewhere outcomes instead of
        vanishing mid-decode. Pass an existing
        ``distributed.PreemptionGuard`` or let one be installed."""
        if guard is None:
            from ..distributed import PreemptionGuard
            guard = PreemptionGuard()
        self._guard = guard
        return guard

    def request(self, rid: str) -> Request:
        return self._requests[rid]

    def decode_program_count(self) -> int:
        """Compiled-program count of the 1-token decode step — the
        no-retrace contract says this stays 1 no matter how requests
        churn. The only other program is the ``[max_slots, chunk]``
        mixed step (prefill chunks + speculative verify), counted by
        :meth:`mixed_program_count`; ``step_program_counts`` reports
        every step shape so none hides as an uncounted extra program."""
        return int(self._decode_step._cache_size())

    def mixed_program_count(self) -> int:
        """Compiled-program count of the mixed step: pinned at 1 under
        churn once any prefill chunk or verify has dispatched — chunk
        sizes, accept patterns and prefill/decode composition are array
        values (``n_live``/``forced`` lanes), never shapes."""
        return int(self._mixed_step._cache_size())

    def verify_program_count(self) -> int:
        """Speculative verify rides the mixed program (verify is the
        mixed step with draft rows instead of prompt rows): 0 with
        speculation off, else the mixed-step program count."""
        if self._spec is None:
            return 0
        return self.mixed_program_count()

    def step_program_counts(self) -> dict[str, int]:
        """Per-step-shape compiled-program counts. Every step shape the
        engine can dispatch is first-class here, and the O(1)-programs
        contract says each value stays at most 1 no matter how requests
        churn, prompts chunk, or accept patterns vary (asserted by the
        bench drivers and tests/test_serving_spec.py over churn
        epochs)."""
        return {"decode": int(self._decode_step._cache_size()),
                "mixed": int(self._mixed_step._cache_size())}

    def warm_programs(self, *, decode: bool = True,
                      mixed: bool = True) -> None:
        """Compile the step programs with an all-inactive dispatch
        (every row targets the reserved scratch page 0) so benches and
        profilers can separate compile time from steady-state latency
        without fabricating requests. Idempotent — reuses the jit
        caches; ``step_program_counts()`` reads 1/1 afterwards. A
        disagg prefill specialist warms with ``decode=False`` so the
        phase-split contract (``{"decode": 0, "mixed": 1}``, SERVING.md
        "Disaggregated serving") survives warming."""
        S, M, K = self.max_slots, self.max_pages_per_slot, self._chunk
        zi = jnp.zeros((S,), jnp.int32)
        zb = jnp.zeros((S,), bool)
        ones = jnp.ones((S,), jnp.float32)
        gt = jnp.ones((S,), bool)
        tables = jnp.zeros((S, M), jnp.int32)
        if decode:
            _, _, pools = self._decode_step(
                self._state, self.pool.pools, zi, tables, zi, zb,
                ones, ones, gt, zi, zi, *self._lora_args())
            self.pool.pools = pools
        if mixed:
            _, _, _, pools = self._mixed_step(
                self._state, self.pool.pools,
                jnp.zeros((S, K), jnp.int32),
                tables, zi, zb, zi, zb, ones, ones, gt, zi, zi,
                *self._lora_args())
            self.pool.pools = pools
        self._note_retraces()

    def pipeline_bubble_frac(self, waves: int | None = None) -> float:
        """Idle-stage fraction of the pipelined mixed step: a ring of
        ``pp`` stages over ``W`` waves runs ``W + pp - 1`` ticks of
        which ``pp - 1`` are fill/drain — the bubble is
        ``(pp - 1) / (W + pp - 1)``. At ``waves == 1`` (the unwaved,
        naive sequential schedule) this is ``(pp - 1) / pp``;
        microbatching with ``waves == pp`` shrinks it to
        ``(pp - 1) / (2 pp - 1)`` — strictly below. 0.0 when pp=1."""
        if self.pp <= 1:
            return 0.0
        W = int(waves) if waves is not None else self._pp_waves
        return (self.pp - 1) / (W + self.pp - 1)

    def stats(self) -> dict:
        return {"steps": self._steps,
                "pool": self.pool.stats(),
                "queue_depth": self.scheduler.queue_depth,
                "running": len(self.scheduler.running),
                "preemptions": self.scheduler.num_preemptions,
                "draining": self._draining,
                "decode_programs": self.decode_program_count(),
                "step_programs": self.step_program_counts(),
                # the pow2 bucket family is gone: every prefill token
                # flows through the ONE mixed program
                "prefill_programs": self.mixed_program_count(),
                "prefix_cache": self.prefix_cache,
                "kv_quant": self.kv_quant,
                "host_tier": self.pool.host_tier is not None,
                "speculative": self._spec is not None,
                "chunked": self.chunked,
                "prefill_chunk": self.prefill_chunk,
                "snapshots": self.snapshot_store is not None,
                "snapshot_interval": self.snapshot_interval,
                "tp": self.tp,
                "pp": self.pp,
                "pp_waves": self._pp_waves,
                "pipeline_bubble_frac": self.pipeline_bubble_frac(),
                "fair": self.scheduler.fair,
                "brownout": self._brownout is not None,
                "brownout_level": self._brownout_level,
                "lora": (self.adapters.stats()
                         if self.adapters is not None else None),
                "tracing": self.tracer.enabled}

    @property
    def brownout_level(self) -> int:
        """Current brownout ladder level (0 = normal service)."""
        return self._brownout_level

    # ------------------------------------------------------------------
    # robustness internals
    # ------------------------------------------------------------------

    def _preemption_pending(self) -> bool:
        return (self._guard is not None and self._guard.preempted
                and not self._draining)

    def _trace_finish(self, req: Request, reason: str | None) -> None:
        """Request-track terminal marker (the scheduler already closed
        the request's queued/running span)."""
        tr = self.tracer
        if tr.enabled:
            tr.instant("finish", track=req.rid, reason=reason or "",
                       tokens=len(req.tokens))
            tr.bump("finishes")

    def _dump_flight(self, reason: str, snapshot: dict | None = None):
        """Auto-dump the attached flight recorder at a terminal
        condition; returns the dump path (None without a recorder — and
        an unwritable destination never masks the original failure)."""
        if self.flight_recorder is None:
            return None
        try:
            return self.flight_recorder.dump(reason, snapshot=snapshot)
        except OSError:
            return None

    def _expire_deadlines(self, events: list[dict]) -> None:
        """Step-boundary deadline enforcement on the injectable metrics
        clock: a waiting request past max_queue_wait_s (or its overall
        deadline_s) and a running request past deadline_s both finish
        with ``finish_reason="timeout"``."""
        now = self.metrics.now()
        for req in list(self.scheduler.waiting):
            waited = now - req.arrival_t
            if ((req.deadline_s is not None and waited >= req.deadline_s)
                    or (req.max_queue_wait_s is not None
                        and waited >= req.max_queue_wait_s)):
                self._finish_abnormal(req, "timeout", events)
        for req in list(self.scheduler.running.values()):
            if (req.deadline_s is not None
                    and now - req.arrival_t >= req.deadline_s):
                self._finish_abnormal(req, "timeout", events)

    def _update_brownout(self, events: list[dict]) -> None:
        """One hysteresis tick of the brownout ladder (see
        :class:`BrownoutConfig`): escalate one level when the queue is
        over the high watermark (depth, or oldest queued wait), step
        back down one level when it is under the low watermark, and
        never move twice within ``dwell_steps`` — a single-step spike
        cannot flap the ladder. Level 3 sheds lowest-priority queued
        requests here. Pure host-side policy: transitions change a
        budget scalar, a drafter skip, and queue membership — never a
        compiled shape, so ``step_program_counts()`` is pinned across
        every transition."""
        cfg = self._brownout
        now = self.metrics.now()
        depth = self.scheduler.queue_depth
        head_wait = max((now - r.arrival_t
                         for r in self.scheduler.waiting), default=0.0)
        hot = depth >= cfg.high_queue or (
            cfg.high_wait_s is not None and head_wait >= cfg.high_wait_s)
        cool = depth <= cfg.low_queue and (
            cfg.low_wait_s is None or head_wait <= cfg.low_wait_s)
        level = self._brownout_level
        if self._steps - self._brownout_since >= cfg.dwell_steps:
            new = level
            if hot and level < 3:
                new = level + 1
            elif cool and level > 0:
                new = level - 1
            if new != level:
                self._brownout_level = new
                self._brownout_since = self._steps
                self.metrics.on_brownout_transition(level, new)
                self.tracer.instant("brownout", level=new, queue=depth)
                # chaos site: a fault here models the overload
                # controller crashing mid-transition (path "old->new")
                _fault.trip("serving.brownout", step=self._steps,
                            path=f"{level}->{new}")
        if self._brownout_level >= 3:
            self._shed_queued(events)
        self.metrics.on_brownout_level(self._brownout_level)

    def _shed_queued(self, events: list[dict]) -> None:
        """Brownout level 3: shed the lowest-priority queued requests
        (youngest first within a priority class — the oldest work is
        closest to its SLO and is spared longest) until the queue is
        back at the high watermark. ``finish_reason="shed"`` is
        terminal on THIS engine but retryable fleet-wide — the
        router's shed events carry ``retry_after_s``."""
        cfg = self._brownout
        while self.scheduler.queue_depth > cfg.high_queue:
            victim = min(self.scheduler.waiting,
                         key=lambda r: (r.priority, -r.arrival_seq))
            self.metrics.on_shed(victim.tenant, victim.priority)
            self.tracer.instant("brownout_shed", track=victim.rid,
                                priority=victim.priority)
            self._finish_abnormal(victim, "shed", events)

    def _flush_waiting(self, events: list[dict]) -> None:
        """Draining: nothing waits — evict the queue as retriable
        ``preempted`` outcomes (covers preemption requeues mid-drain)."""
        for req in list(self.scheduler.waiting):
            self._finish_abnormal(req, "preempted", events)

    def _finish_abnormal(self, req: Request, reason: str,
                         events: list[dict]) -> None:
        if reason == "nonfinite":
            # poison containment: deregister the pages from the prefix
            # index NOW (no future request may match NaN content) and
            # mark them scrub-on-zero. The scrub itself happens when the
            # last reference drops — pages shared with a live request
            # are never zeroed under the reader; pages this request
            # holds alone are scrubbed as its release lands. (A NaN left
            # behind would break the pool's masked-garbage-is-exact-zero
            # invariant: additive masking cannot silence a NaN —
            # NaN + -1e30 is still NaN.)
            self.pool.quarantine(req.pages)
            self._dump_flight("nonfinite", {"rid": req.rid,
                                            "step": self._steps})
        self.scheduler.finish(req, self.pool, reason)
        self.metrics.on_outcome(reason)
        self.metrics.on_finish(req.rid, reason)
        self._trace_finish(req, reason)
        if self.snapshot_store is not None and reason != "preempted":
            # terminal here AND fleet-wide — but a "preempted" eviction
            # is retry-elsewhere, and its snapshot is exactly what lets
            # the retry be a bounded replay instead of a full one
            self.snapshot_store.drop(req.rid)
        events.append({"rid": req.rid, "token": None, "finished": True,
                       "finish_reason": reason})

    def _scrub_pages(self, pages: list[int]) -> None:
        self.pool.scrub(pages)

    def _poison_pages(self, req: Request) -> None:
        """Fault-action callback (``action="poison"``): NaN the
        request's LAST KV page in layer 0 — its next decode step reads
        the NaN through its own block table (additive masking cannot
        silence a NaN) and its logits go non-finite, while no other
        slot can see the page. The last page — not the first: under
        prefix caching the leading pages may be SHARED cached pages,
        and poisoning one would blast every request mapping it. The
        trailing page is never in the prefix index while its owner
        runs (only full prompt pages are registered at the final
        chunk; the partial tail waits for release), so it is always
        private.

        Only kv head 0 is poisoned — under TP that head lives on ONE
        shard, modelling single-device corruption in a TP group; the
        NaN still reaches every slot output (o_proj mixes all query
        heads, and at tp>1 the attention-block psum broadcasts it to
        every shard), so the quarantine is fleet-wide either way."""
        if not req.pages:
            return
        page = req.pages[-1]
        pk, pv = self.pool.pools[0]
        from ..quantization.serving import QuantizedKV
        if self.pool.stacked:
            # pp pool: pools[0] is the stacked [L, pages, ...] pair —
            # poison layer 0 of the page (stage 0's slice; the NaN
            # still reaches every stage through the activation ring)
            if isinstance(pk, QuantizedKV):
                pk = QuantizedKV(pk.q,
                                 pk.scale.at[0, page, :, 0].set(jnp.nan))
            else:
                pk = pk.at[0, page, :, 0].set(jnp.nan)
            self.pool.pools[0] = (pk, pv)
            return
        if isinstance(pk, QuantizedKV):
            # int8 codes cannot hold a NaN — poison the page's fp32
            # SCALE row instead: NaN * code propagates through the
            # dequant into the attention output exactly like a poisoned
            # fp page (and the quarantine scrub must therefore zero
            # scales as well as codes — tested in test_serving_quant)
            self.pool.pools[0] = (
                QuantizedKV(pk.q, pk.scale.at[page, :, 0].set(jnp.nan)),
                pv)
        else:
            self.pool.pools[0] = (pk.at[page, :, 0].set(jnp.nan), pv)

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------

    def _lora_args(self, atable=None) -> tuple:
        """Trailing step-program args when LoRA serving is on: the
        ``[max_slots]`` adapter-table lane (slot -> AdapterPool slot)
        plus the pool's padded device buffers. Empty tuple when off, so
        the base engine's call signature — and compiled program — is
        byte-identical to the pre-LoRA engine."""
        if self.adapters is None:
            return ()
        if atable is None:
            atable = np.zeros((self.max_slots,), np.int32)
        return (jnp.asarray(atable, jnp.int32), self.adapters.buffers())

    def _slot_atable(self) -> np.ndarray:
        """The adapter-table lane for the CURRENT running set (0 for
        free slots — the identity adapter)."""
        atable = np.zeros((self.max_slots,), np.int32)
        for slot, req in self.scheduler.running.items():
            atable[slot] = req.adapter_slot
        return atable

    def _build_decode_step(self):
        from ..nn.module import functional_call
        model = self.model

        def decode_step(state, pools, tok, tables, seq_lens, active,
                        temps, top_ps, greedy, seeds, counts,
                        atable=None, lbuf=None):
            # multi-tenant LoRA: atable is the [max_slots] adapter-table
            # lane (slot -> AdapterPool slot; 0 = identity) and lbuf the
            # pool's padded A/B buffers + scales. A lora engine passes
            # them on EVERY call, a base engine never does — either way
            # one treedef, one compiled program.
            lora = None if lbuf is None else (atable, lbuf[0], lbuf[1])
            (logits, pools), _ = functional_call(
                model, state, tok[:, None], None, pools, 0,
                (tables, seq_lens, active), lora=lora, training=False)
            last = logits[:, -1]
            # per-slot poison sentinel: rows are independent, so a
            # non-finite row indicts exactly one slot
            ok = jnp.all(jnp.isfinite(last.astype(jnp.float32)), axis=-1)
            nt = _sample_rows(last, temps, top_ps, greedy, seeds, counts)
            return nt, ok, pools

        if self._tp is None:
            return jax.jit(decode_step)
        tp = self._tp
        if tp.pp > 1:
            # pp>1: the forward routes through the staged pipeline ring
            # (TPContext.staged_forward, one wave — decode is a single
            # row per slot) instead of the flat model; the sampling tail
            # is byte-identical, running on the replicated post-gather
            # logits, so the fold_in contract and bitwise parity vs the
            # tp-only engine hold
            def decode_step_pp(state, pools, tok, tables, seq_lens,
                               active, temps, top_ps, greedy, seeds,
                               counts):
                logits, pools = tp.staged_forward(
                    state, pools, tok[:, None], tables, seq_lens, active,
                    None, waves=1)
                last = logits[:, -1]
                ok = jnp.all(jnp.isfinite(last.astype(jnp.float32)),
                             axis=-1)
                nt = _sample_rows(last, temps, top_ps, greedy, seeds,
                                  counts)
                return nt, ok, pools
            return tp.compile_step(decode_step_pp, self._state,
                                   self.pool.pools, n_lanes=9, n_lead=2)
        # tp>1: the SAME body compiles as ONE shard_map program over the
        # mp axis — state/pools come in sharded, the 9 host-built lanes
        # replicated, tokens/ok out replicated (sampling ran on the
        # all-gathered logits, identically on every shard)
        return self._tp.compile_step(decode_step, self._state,
                                     self.pool.pools, n_lanes=9, n_lead=2)

    def _build_mixed_step(self):
        """THE mixed step: ONE fixed-shape ``[max_slots, chunk]``
        program for the engine's lifetime, serving prefill chunks,
        decode, speculative verify, and any per-slot mixture.

        Per slot, ``n_live`` new tokens start at pool position
        ``seq_lens``: row j is written at ``seq_lens + j`` and attends
        causally up to itself (rows >= n_live and inactive slots write
        scratch page 0). Two slot flavors share the shape:

        - ``forced`` (a prefill chunk): the rows are the next n_live
          prompt tokens, teacher-forced — every row is accepted by
          construction (``m = n_live - 1``) and only the LAST row's
          sample can matter (the first token of a fresh request's
          stream, on its final chunk);
        - verify/decode (not forced): row 0 is the ordinary decode
          input (the last generated token) and rows 1..n_live-1 are
          the drafter's guesses; draft row j is ACCEPTED iff it equals
          the row j-1 sample, the Leviathan accept/reject rule.

        Every row is sampled under the engine's standard contract —
        ``fold_in(PRNGKey(seed), counts + j)``, the exact key the
        sequential engine would use for that token index — so emitted
        streams are bitwise identical to sequential decode (greedy and
        sampled) no matter how prompts chunk or what the drafter
        proposed. Rejected live rows are zeroed IN-PROGRAM (fixed-shape
        scatter: rejected rows target their real (page, offset),
        everything else targets scratch (0, 0)) so no garbage outlives
        the step — chunk sizes and accept patterns are data, never
        shapes."""
        from ..nn.module import functional_call
        model = self.model
        ps = self.page_size

        def mixed_step(state, pools, toks, tables, seq_lens, active,
                       n_live, forced, temps, top_ps, greedy, seeds,
                       counts, atable=None, lbuf=None):
            lora = None if lbuf is None else (atable, lbuf[0], lbuf[1])
            (logits, pools), _ = functional_call(
                model, state, toks, None, pools, 0,
                (tables, seq_lens, active, n_live), lora=lora,
                training=False)
            S, K, V = logits.shape
            rows = jnp.arange(K)
            live = rows[None, :] < n_live[:, None]            # [S, K]
            # per-slot poison sentinel over the LIVE rows only (padded
            # rows read scratch and may be anything)
            ok = jnp.all(jnp.where(live[..., None],
                                   jnp.isfinite(logits.astype(jnp.float32)),
                                   True), axis=(1, 2))
            # sample all S*K rows with the row's own token index —
            # logits stay in the model dtype so argmax/softmax see the
            # same bits the 1-token decode step would
            samp = _sample_rows(
                logits.reshape(S * K, V),
                jnp.repeat(temps, K), jnp.repeat(top_ps, K),
                jnp.repeat(greedy, K), jnp.repeat(seeds, K),
                (counts[:, None] + rows[None, :]).reshape(-1),
            ).reshape(S, K)
            # accepted count m: a forced (chunk) slot accepts all its
            # rows — its tokens are the prompt, not guesses; a verify
            # slot accepts the longest prefix of live draft rows
            # matching the previous row's sample
            match = (toks[:, 1:] == samp[:, :-1]) & live[:, 1:]
            m = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                        axis=1)                               # [S]
            m = jnp.where(forced, n_live - 1, m)
            # in-program rollback: zero the rejected live rows at their
            # real (page, offset); all other rows target scratch (0, 0).
            # Speculatively-written pages are always private to their
            # request (shared full pages are immutable, COW copies
            # partials), so the zeroing can never hit foreign KV. A
            # forced slot has no rejected rows (rows > n_live - 1 are
            # not live), so chunk writes always survive.
            pos = seq_lens[:, None] + rows[None, :]           # [S, K]
            rej = live & (rows[None, :] > m[:, None]) & active[:, None]
            page = jnp.take_along_axis(tables, pos // ps, axis=1)
            page = jnp.where(rej, page, 0)
            off = jnp.where(rej, pos % ps, 0)
            pools = [(KVCachePool._pos_zero(pk, page, off),
                      KVCachePool._pos_zero(pv, page, off))
                     for pk, pv in pools]
            return samp, m, ok, pools

        if self._tp is None:
            return jax.jit(mixed_step)
        tp = self._tp
        if tp.pp > 1:
            # pp>1: the forward is the microbatched pipeline ring — the
            # chunk splits into waves that overlap across stages — and
            # everything after the logits (finite sentinel, Leviathan
            # accept, in-program rollback) repeats the tp body verbatim
            # on the replicated values, except the rollback scatter
            # addresses the stacked [L, pages, ...] pool layout
            waves = self._pp_waves

            def mixed_step_pp(state, pools, toks, tables, seq_lens,
                              active, n_live, forced, temps, top_ps,
                              greedy, seeds, counts):
                logits, pools = tp.staged_forward(
                    state, pools, toks, tables, seq_lens, active, n_live,
                    waves=waves)
                S, K, V = logits.shape
                rows = jnp.arange(K)
                live = rows[None, :] < n_live[:, None]        # [S, K]
                ok = jnp.all(jnp.where(
                    live[..., None],
                    jnp.isfinite(logits.astype(jnp.float32)),
                    True), axis=(1, 2))
                samp = _sample_rows(
                    logits.reshape(S * K, V),
                    jnp.repeat(temps, K), jnp.repeat(top_ps, K),
                    jnp.repeat(greedy, K), jnp.repeat(seeds, K),
                    (counts[:, None] + rows[None, :]).reshape(-1),
                ).reshape(S, K)
                match = (toks[:, 1:] == samp[:, :-1]) & live[:, 1:]
                m = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                            axis=1)
                m = jnp.where(forced, n_live - 1, m)
                pos = seq_lens[:, None] + rows[None, :]
                rej = live & (rows[None, :] > m[:, None]) & active[:, None]
                page = jnp.take_along_axis(tables, pos // ps, axis=1)
                page = jnp.where(rej, page, 0)
                off = jnp.where(rej, pos % ps, 0)
                pools = [(KVCachePool._pos_zero(pk, page, off, True),
                          KVCachePool._pos_zero(pv, page, off, True))
                         for pk, pv in pools]
                return samp, m, ok, pools
            return tp.compile_step(mixed_step_pp, self._state,
                                   self.pool.pools, n_lanes=11, n_lead=3)
        # tp>1: same body, ONE shard_map program (the rollback scatter is
        # head-local — page/off index the replicated dims, every shard
        # zeroes its own kvh/tp heads of the rejected rows)
        return self._tp.compile_step(mixed_step, self._state,
                                     self.pool.pools, n_lanes=11, n_lead=3)

    # ------------------------------------------------------------------
    # per-step work
    # ------------------------------------------------------------------

    def _run_prefill(self, req: Request, events: list[dict]) -> None:
        """Unchunked (``chunked=False``) admission prefill: run the
        whole uncached suffix through the mixed program NOW, inside the
        admission loop, as forced single-slot passes of up to ``chunk``
        rows each. This is the legacy whole-prompt pacing (the A/B
        baseline arm): registration and first-token emission complete
        before the next admission's prefix lookup, so a same-step burst
        sharing a system prompt still prefills the common prefix
        exactly once — but the step's decode slots wait for the whole
        prompt, which is exactly the head-of-line blocking chunked mode
        removes."""
        tr = self.tracer
        n_valid = req.prefill_target
        cached = req.cached_len
        n_sfx = n_valid - cached
        seq = req.prompt + req.tokens[:-1]
        if n_sfx == 0:
            tr.instant("prefill_cached", track=req.rid, cached=cached)
            # recompute fully served from the prefix cache: the pages
            # already hold the materialized context bit-for-bit and the
            # recompute prefill's prediction would be discarded anyway —
            # no program runs, the stored last token drives the next
            # decode step. (Only reachable for req.tokens non-empty:
            # fresh admissions cap the match at n_valid - 1.)
            return
        S, M, K = self.max_slots, self.max_pages_per_slot, self._chunk
        slot = req.slot
        sp = req.sampling
        tok = 0
        ok_all = True
        with tr.span("prefill", track=req.rid, cached=cached,
                     suffix=n_sfx, chunks=-(-n_sfx // K)):
            start = cached
            while start < n_valid:
                n = min(K, n_valid - start)
                toks = np.zeros((S, K), np.int32)
                toks[slot, :n] = seq[start:start + n]
                tables = np.zeros((S, M), np.int32)
                tables[slot, :len(req.pages)] = req.pages
                seq_lens = np.zeros((S,), np.int32)
                seq_lens[slot] = start
                active = np.zeros((S,), bool)
                active[slot] = True
                n_live = np.zeros((S,), np.int32)
                n_live[slot] = n
                forced = np.zeros((S,), bool)
                forced[slot] = True
                temps = np.ones((S,), np.float32)
                temps[slot] = sp.temperature
                top_ps = np.ones((S,), np.float32)
                top_ps[slot] = sp.top_p
                greedy = np.ones((S,), bool)
                greedy[slot] = not sp.do_sample
                seeds = np.zeros((S,), np.int32)
                seeds[slot] = sp.seed
                counts = np.zeros((S,), np.int32)
                # row j samples with counts + j: anchor the LAST row of
                # the pass on this request's next token index (earlier
                # rows sample at stale indices and are discarded)
                counts[slot] = len(req.tokens) - (n - 1)
                atable = np.zeros((S,), np.int32)
                atable[slot] = req.adapter_slot
                samp, _, ok, new_pools = self._mixed_step(
                    self._state, self.pool.pools, jnp.asarray(toks),
                    jnp.asarray(tables), jnp.asarray(seq_lens),
                    jnp.asarray(active), jnp.asarray(n_live),
                    jnp.asarray(forced), jnp.asarray(temps),
                    jnp.asarray(top_ps), jnp.asarray(greedy),
                    jnp.asarray(seeds), jnp.asarray(counts),
                    *self._lora_args(atable))
                self.pool.pools = new_pools
                samp, ok = self._watched_sync(samp, ok)
                start += n
                tok = int(samp[slot, n - 1])
                if not bool(ok[slot]):
                    ok_all = False
                    break  # NaN cache rows only propagate — stop early
        self._note_retraces()
        if self.kv_quant:
            # quantize-at-scatter observability: error-stat gauge (per-
            # element error <= scale/2) + one trace instant per prefill
            qs = self._qscale_max(req.pages)
            self.metrics.on_kv_quant_scale(qs)
            tr.instant("kv_quantize", track=req.rid,
                       scale_max=round(qs, 6), suffix=n_sfx)
        if _fault.active_plan() is not None:
            try:
                _fault.trip("serving.prefill", step=self._steps,
                            path=req.rid,
                            poison=lambda r=req: self._poison_pages(r))
            except _fault.FaultInjected:
                self._finish_abnormal(req, "injected", events)
                return
        if not ok_all:
            # the prompt itself produced non-finite logits — quarantine
            # at admission, before it ever joins the decode batch
            self._finish_abnormal(req, "nonfinite", events)
            return
        # index the prompt's full pages NOW (not at release) so requests
        # arriving while this one is still decoding can already share
        # its prefix — the staggered shared-system-prompt workload. Full
        # pages are immutable from here on; the trailing partial page
        # keeps filling during decode and is registered at release.
        self.pool.register_prefix(seq[:n_valid], req.pages,
                                  include_partial=False,
                                  namespace=req.adapter_ns)
        if req.tokens:
            return  # recompute after preemption: cache rebuilt, the stored
                    # last token is the next decode input — no new emission
        if req.handoff:
            # disaggregated serving (unchunked arm): same publish-
            # instead-of-emit rule as the mixed-step final chunk
            self._handoff_finish(req, events)
            return
        self._emit(req, tok, events)

    def _qscale_max(self, pages: list[int]) -> float:
        """Max absmax scale over the request's pages across all layers
        — the bounded-dequant-error stat (per-element error <= scale/2)
        the metrics gauge and ``kv_quantize`` trace instants report."""
        idx = jnp.asarray(pages, jnp.int32)
        qs = 0.0
        for pk, pv in self.pool.pools:
            qs = max(qs, float(jnp.max(pk.scale[idx])),
                     float(jnp.max(pv.scale[idx])))
        return qs

    def _plan_chunks(self, budget: int) -> dict[int, int]:
        """slot -> n_new: this step's prefill chunks, FCFS by arrival
        over the partially-prefilled slots under the remaining prefill
        token budget. The OLDEST prefilling slot always advances at
        least one token even with the budget exhausted (chunked
        admission charges no suffix, so this is the no-starvation
        guarantee that keeps stall detection honest); younger slots
        never jump the budget queue."""
        plan: dict[int, int] = {}
        if not self.chunked:
            return plan
        C = self._chunk
        Kw = C // max(self.scheduler.pp_waves, 1)
        prefilling = sorted(
            ((slot, req) for slot, req in self.scheduler.running.items()
             if req.prefilling),
            key=lambda sr: sr[1].arrival_seq)
        for slot, req in prefilling:
            need = req.prefill_target - req.context_len
            cap = budget if plan else max(budget, 1)
            n = min(C, need, cap)
            if n <= 0:
                break
            if n < need and n > Kw:
                # wave alignment (pp microbatching): a non-final bite
                # rounds down to whole waves of the microbatched mixed
                # step, so no wave runs half-empty mid-prompt. Pure
                # pacing — chunk boundaries never change the emitted
                # stream (chunked-prefill parity contract). At
                # pp_waves=1, Kw == C >= n and this never fires.
                n = (n // Kw) * Kw
            plan[slot] = n
            budget -= n
        return plan

    def _run_batch(self, events: list[dict], budget: int) -> int:
        """Dispatch this step's model work: plan prefill chunks under
        the remaining token budget, then route — any chunk or draft
        rows go through the ONE mixed program (decode slots ride along
        in the same dispatch); a pure-decode step keeps the cheap
        ``[max_slots]`` decode program. Returns the number of prefill
        chunk tokens dispatched (progress accounting for the stall
        detector)."""
        if _fault.active_plan() is not None:
            for req in list(self.scheduler.running.values()):
                if req.prefilling:
                    continue  # serving.prefill trips at chunk dispatch
                try:
                    _fault.trip("serving.decode", step=self._steps,
                                path=req.rid,
                                poison=lambda r=req: self._poison_pages(r))
                except _fault.FaultInjected:
                    self._finish_abnormal(req, "injected", events)
            if not self.scheduler.running:
                return 0
        plan = self._plan_chunks(budget)
        has_drafts = self._spec is not None and any(
            req.draft_tokens for req in self.scheduler.running.values())
        if plan or has_drafts:
            return self._run_mixed(events, plan)
        self._run_decode(events)
        return 0

    def _run_decode(self, events: list[dict]) -> None:
        tr = self.tracer
        S, M = self.max_slots, self.max_pages_per_slot
        with tr.span("decode_dispatch", slots=len(self.scheduler.running)):
            tok = np.zeros((S,), np.int32)
            tables = np.zeros((S, M), np.int32)
            seq_lens = np.zeros((S,), np.int32)
            active = np.zeros((S,), bool)
            temps = np.ones((S,), np.float32)
            top_ps = np.ones((S,), np.float32)
            greedy = np.ones((S,), bool)
            seeds = np.zeros((S,), np.int32)
            counts = np.zeros((S,), np.int32)
            for slot, req in self.scheduler.running.items():
                tok[slot] = req.tokens[-1]
                tables[slot, :len(req.pages)] = req.pages
                seq_lens[slot] = req.context_len
                active[slot] = True
                temps[slot] = req.sampling.temperature
                top_ps[slot] = req.sampling.top_p
                greedy[slot] = not req.sampling.do_sample
                seeds[slot] = req.sampling.seed
                counts[slot] = len(req.tokens)
            nt, ok, new_pools = self._decode_step(
                self._state, self.pool.pools, jnp.asarray(tok),
                jnp.asarray(tables), jnp.asarray(seq_lens),
                jnp.asarray(active), jnp.asarray(temps),
                jnp.asarray(top_ps), jnp.asarray(greedy),
                jnp.asarray(seeds), jnp.asarray(counts),
                *self._lora_args(self._slot_atable()))
            self.pool.pools = new_pools
        self._note_retraces()
        nt, ok = self._watched_sync(nt, ok)
        with tr.span("sample_emit"):
            for slot, req in list(self.scheduler.running.items()):
                req.context_len += 1  # this step's KV write at old
                                      # context_len
                if not ok[slot]:
                    # poison quarantine: only this slot finishes;
                    # survivors' rows were computed independently and
                    # stay bitwise intact
                    self._finish_abnormal(req, "nonfinite", events)
                    continue
                self._emit(req, int(nt[slot]), events)

    def _run_mixed(self, events: list[dict], plan: dict[int, int]) -> int:
        """One mixed dispatch: the planned prefill chunks (teacher-
        forced prompt rows) and every decoding slot (decode input +
        drafts) share the fixed-shape ``[max_slots, chunk]`` program.
        Chunk slots advance ``context_len`` and emit only on their
        FINAL chunk — which is also when the prompt's full pages commit
        to the prefix index (first-writer-wins; a request preempted
        mid-prompt registers nothing). Decode slots emit their accepted
        sample prefix plus the bonus correction sample — bitwise the
        tokens sequential decode would have produced."""
        tr = self.tracer
        sched = self.scheduler
        S, M, K = self.max_slots, self.max_pages_per_slot, self._chunk
        # the plan may be stale by one preemption (ensure_decode_pages
        # ran in between) — keep only slots that still owe chunks
        plan = {slot: n for slot, n in plan.items()
                if slot in sched.running and sched.running[slot].prefilling}
        toks = np.zeros((S, K), np.int32)
        tables = np.zeros((S, M), np.int32)
        seq_lens = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        n_live = np.zeros((S,), np.int32)
        forced = np.zeros((S,), bool)
        temps = np.ones((S,), np.float32)
        top_ps = np.ones((S,), np.float32)
        greedy = np.ones((S,), bool)
        seeds = np.zeros((S,), np.int32)
        counts = np.zeros((S,), np.int32)
        n_drafted: dict[int, int] = {}
        chunk_tokens = 0
        for slot, req in sched.running.items():
            if req.prefilling and slot not in plan:
                continue  # out of budget this step: the slot sits out
            sp = req.sampling
            tables[slot, :len(req.pages)] = req.pages
            seq_lens[slot] = req.context_len
            active[slot] = True
            temps[slot] = sp.temperature
            top_ps[slot] = sp.top_p
            greedy[slot] = not sp.do_sample
            seeds[slot] = sp.seed
            if slot in plan:
                n = plan[slot]
                seq = req.prompt + req.tokens[:-1]
                toks[slot, :n] = seq[req.context_len:req.context_len + n]
                n_live[slot] = n
                forced[slot] = True
                # row j samples with counts + j: anchor the LAST chunk
                # row on this request's next token index (mid-chunk
                # rows sample at stale indices and are discarded)
                counts[slot] = len(req.tokens) - (n - 1)
                chunk_tokens += n
                if tr.enabled:
                    tr.instant("chunk", track=req.rid,
                               start=int(req.context_len), n=n)
                    tr.bump("chunks")
            else:
                d = req.draft_tokens
                toks[slot, 0] = req.tokens[-1]
                if d:
                    toks[slot, 1:1 + len(d)] = d
                n_live[slot] = 1 + len(d)
                n_drafted[slot] = len(d)
                counts[slot] = len(req.tokens)
        self.metrics.on_mixed_step(
            chunk_tokens, len(n_drafted), len(plan),
            sum(1 for r in sched.running.values() if r.prefilling))
        if tr.enabled and self._pp_waves > 1:
            # stage waves run inside the one compiled mixed program, so
            # the per-wave instants are logical markers emitted at
            # dispatch (the device timeline can't be split from host)
            for w in range(self._pp_waves):
                tr.instant("pp_wave", wave=w, width=K // self._pp_waves,
                           pp=self.pp)
        with tr.span("mixed_dispatch", slots=len(plan) + len(n_drafted),
                     chunk_tokens=chunk_tokens,
                     drafts=sum(n_drafted.values())):
            samp, acc, ok, new_pools = self._mixed_step(
                self._state, self.pool.pools, jnp.asarray(toks),
                jnp.asarray(tables), jnp.asarray(seq_lens),
                jnp.asarray(active), jnp.asarray(n_live),
                jnp.asarray(forced), jnp.asarray(temps),
                jnp.asarray(top_ps), jnp.asarray(greedy),
                jnp.asarray(seeds), jnp.asarray(counts),
                *self._lora_args(self._slot_atable()))
            self.pool.pools = new_pools
        self._note_retraces()
        samp, acc, ok = self._watched_sync(samp, acc, ok)
        # serving.prefill fault trips for the chunk slots, mirroring
        # the legacy prefill site: after the write, before the ok check
        # and before any registration — an injected chunk failure can
        # never index its pages
        if _fault.active_plan() is not None:
            for slot in list(plan):
                req = sched.running.get(slot)
                if req is None:
                    continue
                try:
                    _fault.trip("serving.prefill", step=self._steps,
                                path=req.rid,
                                poison=lambda r=req: self._poison_pages(r))
                except _fault.FaultInjected:
                    req.context_len += plan.pop(slot)
                    self._finish_abnormal(req, "injected", events)
        with tr.span("sample_emit"):
            participants = ([s for s in plan if s in sched.running]
                            + [s for s in n_drafted if s in sched.running])
            for slot in participants:
                req = sched.running.get(slot)
                if req is None:
                    continue
                if slot in plan:
                    n = plan[slot]
                    req.context_len += n
                    if not ok[slot]:
                        # the prompt chunk produced non-finite logits —
                        # quarantine before it ever joins the decode
                        # batch (and before any registration)
                        self._finish_abnormal(req, "nonfinite", events)
                        continue
                    if req.prefilling:
                        continue  # mid-prompt: more chunks owed
                    # FINAL chunk: commit the prompt's full pages to
                    # the prefix index now (first-writer-wins in the
                    # pool; the trailing partial page keeps filling
                    # during decode and is registered at release)
                    seq = req.prompt + req.tokens[:-1]
                    self.pool.register_prefix(seq[:req.prefill_target],
                                              req.pages,
                                              include_partial=False,
                                              namespace=req.adapter_ns)
                    if self.kv_quant:
                        qs = self._qscale_max(req.pages)
                        self.metrics.on_kv_quant_scale(qs)
                        tr.instant("kv_quantize", track=req.rid,
                                   scale_max=round(qs, 6), suffix=n)
                    if req.tokens:
                        continue  # recompute after preemption: cache
                                  # rebuilt, the stored last token is
                                  # the next decode input
                    if req.handoff:
                        # disaggregated serving: publish the finished
                        # KV instead of emitting — the decode replica
                        # recomputes this same final row and emits the
                        # bitwise-identical first token itself
                        self._handoff_finish(req, events)
                        continue
                    self._emit(req, int(samp[slot, n - 1]), events)
                else:
                    n_draft = n_drafted[slot]
                    req.draft_tokens = []
                    C0 = req.context_len
                    if not ok[slot]:
                        # poison quarantine, same as the decode path:
                        # only this slot finishes (rows are per-slot
                        # independent)
                        req.context_len += 1
                        self._finish_abnormal(req, "nonfinite", events)
                        continue
                    m = int(acc[slot])
                    if n_draft:
                        self.metrics.on_spec_verify(n_draft, m)
                        self._drafter.observe(req, n_draft, m)
                    # the emitted tokens are the engine's own samples
                    # for rows 0..m — exactly what m + 1 sequential
                    # decode steps would have drawn. A stop (eos)
                    # inside the accept window truncates the emission.
                    emit: list[int] = []
                    for j in range(m + 1):
                        t = int(samp[slot, j])
                        emit.append(t)
                        if ((req.eos_token_id is not None
                             and t == req.eos_token_id)
                                or len(req.tokens) + len(emit)
                                >= req.max_new_tokens):
                            break
                    req.context_len = C0 + len(emit)
                    if len(emit) < m + 1:
                        # accepted-but-unused tail beyond an in-window
                        # stop: rewind those positions to zero before
                        # the pages can be released/registered (token-
                        # granular masked-garbage-is-zero)
                        self.pool.rewind(req.pages, C0 + len(emit),
                                         C0 + m + 1)
                    if tr.enabled and n_draft > m:
                        tr.instant("rollback", track=req.rid,
                                   rejected=n_draft - m, accepted=m)
                        tr.bump("spec_rejected_tokens", n_draft - m)
                    for t in emit:
                        self._emit(req, t, events)
        return chunk_tokens

    def _note_retraces(self) -> None:
        """Retrace sentinel, one per step shape ("decode", "mixed"):
        the no-retrace contract says every entry of
        ``step_program_counts()`` stays at 1; any growth lands a
        compile bar + counter bump in the trace right where the
        regression happened."""
        tr = self.tracer
        if not tr.enabled:
            return
        for name, n in self.step_program_counts().items():
            seen = self._step_traces.get(name, 0)
            if n != seen:
                tr.instant("compile", program=name, programs=n)
                tr.bump("compiles", n - seen)
                if seen:
                    tr.bump("decode_retraces", n - seen)
                self._step_traces[name] = n

    def _watched_sync(self, *arrays):
        """The engine's blocking device sync (np.asarray) under the
        watchdog — a hung device shows up here, so this is where the
        watchdog looks (and where the flight recorder's post-mortem
        hook dumps the event ring before any kill action fires)."""
        from ..distributed.watchdog import default_watchdog
        wd = self._watchdog if self._watchdog is not None \
            else default_watchdog()
        if self.flight_recorder is not None and id(wd) not in self._wd_hooked:
            # one hook per watchdog instance
            self._wd_hooked.add(id(wd))
            recorder = self.flight_recorder

            def _post_mortem(task_rec, _fr=recorder):
                _fr.dump("watchdog_timeout", snapshot={
                    "task": task_rec.name,
                    "meta": {k: repr(v) for k, v in task_rec.meta.items()}})

            wd.post_mortem_hooks.append(_post_mortem)
        with wd.task("serving.step", timeout=self.step_timeout_s,
                     step=self._steps, slots=len(self.scheduler.running)):
            with self.tracer.span("device_sync"):
                return tuple(np.asarray(a) for a in arrays)

    # ------------------------------------------------------------------
    # speculative decoding (serving/speculative.py)
    # ------------------------------------------------------------------

    def _propose_drafts(self) -> None:
        """Host-side draft proposal for every decoding slot (a slot
        still mid-prefill neither decodes nor drafts). The draft count
        is capped so the mixed step can never write beyond the
        request's admission-checked page/position budget: at most k-1
        rows, at most what the remaining token budget could accept
        (m + 1 emits <= remaining), and never past the slot's page
        table or the rope table."""
        spec, drafter = self._spec, self._drafter
        max_pos = min(self.max_pages_per_slot * self.page_size,
                      self.model.config.max_position_embeddings)
        with self.tracer.span("draft",
                              slots=len(self.scheduler.running)):
            for req in self.scheduler.running.values():
                if req.prefilling or not req.tokens:
                    req.draft_tokens = []
                    continue
                cap = min(spec.k - 1,
                          req.max_new_tokens - len(req.tokens) - 1,
                          max_pos - req.context_len - 1)
                drafts = drafter.propose(req, cap) if cap > 0 else []
                req.draft_tokens = [int(t) for t in drafts[:cap]]
                self.metrics.on_spec_draft(len(req.draft_tokens))

    def _emit(self, req: Request, token: int, events: list[dict]) -> None:
        req.tokens.append(token)
        self.metrics.on_token(req.rid)
        self.tracer.bump("tokens")
        reason = None
        if req.eos_token_id is not None and token == req.eos_token_id:
            reason = "stop"
        elif len(req.tokens) >= req.max_new_tokens:
            reason = "length"
        if reason is not None:
            self.scheduler.finish(req, self.pool, reason)
            self.metrics.on_finish(req.rid, reason)
            self._trace_finish(req, reason)
            if self.snapshot_store is not None:
                # terminal: the store is bounded by LIVE requests
                self.snapshot_store.drop(req.rid)
        events.append({"rid": req.rid, "token": token,
                       "finished": reason is not None,
                       "finish_reason": reason})


def _sample_rows(logits, temps, top_ps, greedy, seeds, counts):
    """Per-slot next-token choice: greedy argmax or nucleus sampling with
    a per-request key stream fold_in(PRNGKey(seed), token_index) —
    independent of slot placement and batch composition, so recompute
    after preemption reproduces the original draws."""
    from ..ops.random import top_p_sampling

    def row(lg, t, p, g, seed, cnt):
        gd = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), cnt)
        probs = jax.nn.softmax(lg.astype(jnp.float32) / t, axis=-1)
        _, idx = top_p_sampling(probs[None], p[None], key=key)
        return jnp.where(g, gd, idx[0, 0].astype(jnp.int32))

    return jax.vmap(row)(logits, temps, top_ps, greedy, seeds, counts)
