"""Continuous-batching serving engine over the paged KV-cache pool.

The decode step is ONE compiled program for the engine's lifetime: it
always runs over the fixed ``[max_slots]`` slot axis, with block tables
``[max_slots, max_pages_per_slot]``, position offsets, the active-slot
mask, and every per-request sampling parameter passed as ARRAY inputs.
Requests joining, finishing, or being preempted only change array
*values*, never shapes or the jaxpr — ``decode_program_count()`` stays
at 1 across arbitrary churn (asserted by tests/test_serving.py).

Prefill runs one admitted request at a time through per-bucket compiled
programs (prompt lengths rounded up to power-of-two page multiples, so
the program count is O(log max_len)): a contiguous forward over the
padded prompt fills a temporary ``[1, L_bucket]`` cache which is then
scattered page-by-page into the pool through the request's block table.
Bucket-padding positions land in the reserved scratch page 0.

Determinism: greedy decode is argmax over logits that are bitwise equal
to ``LlamaForCausalLM.generate()``'s (shared attention core, masked
padding contributes exact zeros — see SERVING.md); sampled requests
draw token *n* with ``fold_in(PRNGKey(seed), n)`` so a preempted and
recomputed request reproduces its original stream regardless of slot
placement or batch composition.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import KVCachePool
from .metrics import ServingMetrics
from .scheduler import Request, SamplingParams, Scheduler

__all__ = ["ServingEngine"]


class ServingEngine:
    def __init__(self, model, num_pages: int, page_size: int,
                 max_slots: int = 4, max_pages_per_slot: int | None = None,
                 prefill_token_budget: int = 2048, kv_dtype=None,
                 clock=None):
        cfg = model.config
        self.model = model
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_pages_per_slot = (max_pages_per_slot
                                   if max_pages_per_slot is not None
                                   else (num_pages - 1))
        self.pool = KVCachePool.from_config(
            cfg, num_pages, page_size,
            dtype=kv_dtype if kv_dtype is not None else jnp.bfloat16)
        self.scheduler = Scheduler(max_slots, prefill_token_budget)
        self.metrics = ServingMetrics(clock)
        self._state = model.state_dict(include_non_persistable_buffer=True)
        self._requests: dict[str, Request] = {}
        self._rid_counter = itertools.count()
        self._steps = 0
        self._decode_step = self._build_decode_step()
        self._prefill_progs: dict[int, object] = {}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def add_request(self, prompt, max_new_tokens: int,
                    sampling: SamplingParams | None = None,
                    eos_token_id: int | None = None,
                    rid: str | None = None) -> str:
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        total = len(prompt) + max_new_tokens
        need = self.pool.pages_for(total)
        if need > self.max_pages_per_slot:
            raise ValueError(
                f"request needs {need} pages "
                f"(max_pages_per_slot={self.max_pages_per_slot})")
        if need > self.pool.capacity:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.pool.capacity} — it could never run")
        rid = rid if rid is not None else f"req-{next(self._rid_counter)}"
        if rid in self._requests:
            raise ValueError(f"duplicate request id {rid!r}")
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      sampling=sampling or SamplingParams(),
                      eos_token_id=eos_token_id)
        self._requests[rid] = req
        self.scheduler.add(req)
        self.metrics.on_arrival(rid)
        return rid

    def step(self) -> list[dict]:
        """One scheduling iteration: admit + prefill newly runnable
        requests, guarantee decode pages (preempting if needed), then one
        batched decode step over every running slot. Returns this step's
        token/finish events."""
        if not self.scheduler.has_work():
            return []
        events: list[dict] = []
        for req in self.scheduler.admit(self.pool):
            self._run_prefill(req, events)
        preempted = self.scheduler.ensure_decode_pages(self.pool)
        for _ in preempted:
            self.metrics.on_preemption()
        if self.scheduler.running:
            self._run_decode(events)
        self.metrics.on_step(self.scheduler.queue_depth,
                             self.pool.utilization())
        self._steps += 1
        return events

    def stream(self):
        """Drive the engine to completion, yielding events as they are
        produced: ``{"rid", "token", "finished", "finish_reason"}``."""
        while self.scheduler.has_work():
            yield from self.step()

    def run_to_completion(self, max_steps: int | None = None) -> dict:
        """Drain the queue; returns {rid: generated token list}."""
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(f"engine did not drain in {steps} steps")
        return {rid: list(r.tokens) for rid, r in self._requests.items()}

    def request(self, rid: str) -> Request:
        return self._requests[rid]

    def decode_program_count(self) -> int:
        """Compiled-program count of the decode step — the no-retrace
        contract says this stays 1 no matter how requests churn."""
        return int(self._decode_step._cache_size())

    def stats(self) -> dict:
        return {"steps": self._steps,
                "pool": self.pool.stats(),
                "queue_depth": self.scheduler.queue_depth,
                "running": len(self.scheduler.running),
                "preemptions": self.scheduler.num_preemptions,
                "decode_programs": self.decode_program_count(),
                "prefill_programs": len(self._prefill_progs)}

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------

    def _build_decode_step(self):
        from ..nn.module import functional_call
        model = self.model

        @jax.jit
        def decode_step(state, pools, tok, tables, seq_lens, active,
                        temps, top_ps, greedy, seeds, counts):
            (logits, pools), _ = functional_call(
                model, state, tok[:, None], None, pools, 0,
                (tables, seq_lens, active), training=False)
            nt = _sample_rows(logits[:, -1], temps, top_ps, greedy,
                              seeds, counts)
            return nt, pools

        return decode_step

    def _bucket(self, n_tokens: int) -> int:
        """Prompt-length bucket: the next power-of-two page count, in
        tokens. Bounds the prefill program count at O(log max_len)."""
        pages = self.pool.pages_for(n_tokens)
        p2 = 1
        while p2 < pages:
            p2 *= 2
        return p2 * self.page_size

    def _prefill_prog(self, L: int):
        if L in self._prefill_progs:
            return self._prefill_progs[L]
        from ..nn.module import functional_call
        model, cfg = self.model, self.model.config
        ps = self.page_size
        n_pages = L // ps
        kv_dtype = self.pool.dtype

        @jax.jit
        def prefill(state, ids, n_valid, scatter_pages, pools,
                    temp, top_p, greedy, seed):
            caches = model.init_kv_caches(1, L, dtype=kv_dtype)
            (logits, caches), _ = functional_call(
                model, state, ids, None, caches, 0, training=False)
            lg = jax.lax.dynamic_index_in_dim(logits[0], n_valid - 1,
                                              axis=0, keepdims=False)
            tok = _sample_rows(lg[None], temp[None], top_p[None],
                               greedy[None], seed[None],
                               jnp.zeros((1,), jnp.int32))[0]
            new_pools = []
            for (ck, cv), (pk, pv) in zip(caches, pools):
                kvh, d = ck.shape[2], ck.shape[3]
                pk = pk.at[scatter_pages].set(
                    ck[0].reshape(n_pages, ps, kvh, d))
                pv = pv.at[scatter_pages].set(
                    cv[0].reshape(n_pages, ps, kvh, d))
                new_pools.append((pk, pv))
            return tok, new_pools

        self._prefill_progs[L] = prefill
        return prefill

    # ------------------------------------------------------------------
    # per-step work
    # ------------------------------------------------------------------

    def _run_prefill(self, req: Request, events: list[dict]) -> None:
        n_valid = req.context_len  # == recompute_len, set by admit()
        L = self._bucket(n_valid)
        n_pages = L // self.page_size
        ids = np.zeros((1, L), np.int32)
        ids[0, :n_valid] = req.prompt + req.tokens[:-1]
        scatter = np.zeros((n_pages,), np.int32)
        scatter[:len(req.pages)] = req.pages
        sp = req.sampling
        tok, new_pools = self._prefill_prog(L)(
            self._state, jnp.asarray(ids), jnp.int32(n_valid),
            jnp.asarray(scatter), self.pool.pools,
            jnp.float32(sp.temperature), jnp.float32(sp.top_p),
            jnp.asarray(not sp.do_sample), jnp.int32(sp.seed))
        self.pool.pools = new_pools
        if req.tokens:
            return  # recompute after preemption: cache rebuilt, the stored
                    # last token is the next decode input — no new emission
        self._emit(req, int(tok), events)

    def _run_decode(self, events: list[dict]) -> None:
        S, M = self.max_slots, self.max_pages_per_slot
        tok = np.zeros((S,), np.int32)
        tables = np.zeros((S, M), np.int32)
        seq_lens = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        temps = np.ones((S,), np.float32)
        top_ps = np.ones((S,), np.float32)
        greedy = np.ones((S,), bool)
        seeds = np.zeros((S,), np.int32)
        counts = np.zeros((S,), np.int32)
        for slot, req in self.scheduler.running.items():
            tok[slot] = req.tokens[-1]
            tables[slot, :len(req.pages)] = req.pages
            seq_lens[slot] = req.context_len
            active[slot] = True
            temps[slot] = req.sampling.temperature
            top_ps[slot] = req.sampling.top_p
            greedy[slot] = not req.sampling.do_sample
            seeds[slot] = req.sampling.seed
            counts[slot] = len(req.tokens)
        nt, new_pools = self._decode_step(
            self._state, self.pool.pools, jnp.asarray(tok),
            jnp.asarray(tables), jnp.asarray(seq_lens), jnp.asarray(active),
            jnp.asarray(temps), jnp.asarray(top_ps), jnp.asarray(greedy),
            jnp.asarray(seeds), jnp.asarray(counts))
        self.pool.pools = new_pools
        nt = np.asarray(nt)
        for slot, req in list(self.scheduler.running.items()):
            req.context_len += 1  # this step's KV write at old context_len
            self._emit(req, int(nt[slot]), events)

    def _emit(self, req: Request, token: int, events: list[dict]) -> None:
        req.tokens.append(token)
        self.metrics.on_token(req.rid)
        reason = None
        if req.eos_token_id is not None and token == req.eos_token_id:
            reason = "stop"
        elif len(req.tokens) >= req.max_new_tokens:
            reason = "length"
        if reason is not None:
            self.scheduler.finish(req, self.pool, reason)
            self.metrics.on_finish(req.rid)
        events.append({"rid": req.rid, "token": token,
                       "finished": reason is not None,
                       "finish_reason": reason})


def _sample_rows(logits, temps, top_ps, greedy, seeds, counts):
    """Per-slot next-token choice: greedy argmax or nucleus sampling with
    a per-request key stream fold_in(PRNGKey(seed), token_index) —
    independent of slot placement and batch composition, so recompute
    after preemption reproduces the original draws."""
    from ..ops.random import top_p_sampling

    def row(lg, t, p, g, seed, cnt):
        gd = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), cnt)
        probs = jax.nn.softmax(lg.astype(jnp.float32) / t, axis=-1)
        _, idx = top_p_sampling(probs[None], p[None], key=key)
        return jnp.where(g, gd, idx[0, 0].astype(jnp.int32))

    return jax.vmap(row)(logits, temps, top_ps, greedy, seeds, counts)
