"""Host-RAM spill tier for the paged KV-cache pool (ROADMAP item 5).

The HBM pool (kv_cache.py) LRU-evicts refcount-0 registered pages when
``alloc`` runs dry — and without this module their content is lost, so
prefix hit-rate collapses exactly when the pool is under pressure.
:class:`HostTier` turns that eviction into a demotion: the evicted
page's bytes (int8 codes AND fp32 scales in quantized mode) are copied
to a bounded host-side pool keyed by the SAME chained content hash the
prefix index uses, namespaced per KV storage format so an fp32, bf16
and int8 cache can never serve each other's bytes. ``match_prefix``
consults HBM first, then this tier; a hit is restored with a
``device_put`` back into a freshly-allocated HBM page at admission time
— on the host side of the step, never inside a compiled program, so the
engine's ``decode_program_count() == 1`` contract is untouched.

Integrity: every entry stores a blake2b-128 digest of its payload
bytes, re-verified at fetch time. A corrupted entry (bit rot, or the
``serving.restore`` fault site's ``poison`` action) is detected,
dropped and counted — the scheduler falls back to recomputing those
tokens, and wrong KV is never served. Spill and restore both honour
the pool's quarantine rules: a quarantined page is never offered to
``spill`` (the pool guards it), and quarantining a page purges its
host-tier entry too.

Tensor parallelism (serving/parallel.py): pool arrays stay GLOBAL
logical ``jax.Array``s whose kv-head dim is sharded across the TP
group, so the ``device_get`` in ``spill`` transparently gathers every
shard's slice into the SAME host payload format a tp=1 pool produces —
host entries (and therefore snapshots built from them) are tp-portable
in both directions. The pool emits a ``shard_gather`` trace instant on
that path when ``tp > 1``.

Accounting rule (SERVING.md "KV tiering & traffic harness"): restored
tokens are cached tokens — they skip recompute FLOPs — but they pay
restore BYTES, so the scheduler charges ``ceil(restored_tokens *
restore_budget_frac)`` against the per-step prefill token budget, the
same budget a partial cache hit's suffix would consume.
"""

from __future__ import annotations

import hashlib
import math
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

__all__ = ["HostTier", "HostPage"]


def _payload_digest(arrays) -> bytes:
    """blake2b-128 over the exact payload bytes, in array order. The
    digest is the corruption detector, not the index key (the chained
    token hash is) — so it covers the BYTES, including scales, not the
    tokens."""
    h = hashlib.blake2b(digest_size=16)
    for a in arrays:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


@dataclass
class HostPage:
    """One demoted page: per-layer numpy arrays in pool order
    (``[k0, v0, k1, v1, ...]``; quantized pools interleave codes and
    scales as ``[kq0, ks0, vq0, vs0, ...]``), plus the integrity digest
    computed at spill time."""
    arrays: list = field(default_factory=list)
    nbytes: int = 0
    digest: bytes = b""


class HostTier:
    """Bounded host-RAM LRU of spilled KV pages.

    Keys are ``(tag, kind, key)``: ``tag`` namespaces the KV storage
    format ("int8" / "bfloat16" / "float32" — same-token pages have
    different bytes under different formats and must never alias),
    ``kind`` is "full" or "partial" (mirroring the pool's two indexes),
    and ``key`` is the pool's chained blake2b-128 content hash. The
    byte budget counts payload bytes only; an entry larger than the
    whole budget is refused (counted as ``spill_dropped``) rather than
    flushing the tier for one page.
    """

    def __init__(self, max_bytes: int = 256 * 1024 * 1024,
                 restore_budget_frac: float = 0.25):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        if restore_budget_frac < 0:
            raise ValueError("restore_budget_frac must be >= 0")
        self.max_bytes = int(max_bytes)
        # fraction of a restored token charged against the scheduler's
        # prefill token budget (restore pays bytes, not FLOPs)
        self.restore_budget_frac = float(restore_budget_frac)
        self._entries: "OrderedDict[tuple, HostPage]" = OrderedDict()
        self._bytes = 0
        self.counters: dict[str, int] = {
            "spilled_pages": 0, "spilled_bytes": 0,
            "restored_pages": 0, "restored_bytes": 0,
            "host_evictions": 0, "spill_dropped": 0,
            "restore_corrupt_detected": 0, "restore_failed": 0,
            "host_hits": 0, "host_misses": 0,
        }

    # ---- accounting ----

    @property
    def pool_bytes(self) -> int:
        """Payload bytes currently resident in the tier."""
        return self._bytes

    @property
    def num_entries(self) -> int:
        return len(self._entries)

    def restore_charge(self, restored_tokens: int) -> int:
        """Prefill-budget tokens a restore of ``restored_tokens`` costs
        (the accounting rule in the module docstring)."""
        if restored_tokens <= 0:
            return 0
        return int(math.ceil(restored_tokens * self.restore_budget_frac))

    def stats(self) -> dict:
        return {"host_pool_bytes": self._bytes,
                "host_pool_pages": len(self._entries),
                "host_capacity_bytes": self.max_bytes,
                **self.counters}

    @staticmethod
    def zero_stats() -> dict:
        """The ``stats()`` key set, all zero — what a pool WITHOUT a
        tier reports, so the metrics/Prometheus schema never depends on
        whether tiering is enabled."""
        return {"host_pool_bytes": 0, "host_pool_pages": 0,
                "host_capacity_bytes": 0,
                "spilled_pages": 0, "spilled_bytes": 0,
                "restored_pages": 0, "restored_bytes": 0,
                "host_evictions": 0, "spill_dropped": 0,
                "restore_corrupt_detected": 0, "restore_failed": 0,
                "host_hits": 0, "host_misses": 0}

    # ---- the spill / restore surface ----

    def put(self, tag: str, kind: str, key: bytes, arrays) -> bool:
        """Demote one page's payload into the tier. Evicts host-LRU
        entries until the new payload fits; refuses (False) a payload
        larger than the whole budget. Re-putting an existing key
        refreshes its content and recency."""
        arrays = [np.ascontiguousarray(np.asarray(a)) for a in arrays]
        nbytes = sum(a.nbytes for a in arrays)
        if nbytes > self.max_bytes:
            self.counters["spill_dropped"] += 1
            return False
        k = (tag, kind, key)
        old = self._entries.pop(k, None)
        if old is not None:
            self._bytes -= old.nbytes
        while self._bytes + nbytes > self.max_bytes and self._entries:
            _, victim = self._entries.popitem(last=False)  # host LRU
            self._bytes -= victim.nbytes
            self.counters["host_evictions"] += 1
        self._entries[k] = HostPage(arrays=arrays, nbytes=nbytes,
                                    digest=_payload_digest(arrays))
        self._bytes += nbytes
        self.counters["spilled_pages"] += 1
        self.counters["spilled_bytes"] += nbytes
        return True

    def has(self, tag: str, kind: str, key: bytes) -> bool:
        """Pure membership probe (no LRU touch) — what ``match_prefix``
        uses to extend the chain walk into the tier."""
        return (tag, kind, key) in self._entries

    def fetch(self, tag: str, kind: str, key: bytes):
        """Promote-read one page's payload, or None. The stored digest
        is re-verified against the payload bytes first: a mismatch
        means the entry was corrupted in host RAM — it is dropped and
        counted, and the caller falls back to recompute (wrong KV is
        never served). A verified hit touches the host LRU; restored-
        bytes accounting happens pool-side where the restore actually
        lands."""
        k = (tag, kind, key)
        entry = self._entries.get(k)
        if entry is None:
            self.counters["host_misses"] += 1
            return None
        if _payload_digest(entry.arrays) != entry.digest:
            del self._entries[k]
            self._bytes -= entry.nbytes
            self.counters["restore_corrupt_detected"] += 1
            return None
        self._entries.move_to_end(k)
        self.counters["host_hits"] += 1
        return entry.arrays

    def on_restored(self, nbytes: int) -> None:
        """Pool callback: one page's payload actually landed back in
        HBM (fetch alone is not a restore — the alloc can still fail)."""
        self.counters["restored_pages"] += 1
        self.counters["restored_bytes"] += int(nbytes)

    def discard(self, tag: str, kind: str, key: bytes) -> bool:
        """Drop an entry (quarantine purge: a poisoned page's content
        must not survive in ANY tier)."""
        entry = self._entries.pop((tag, kind, key), None)
        if entry is None:
            return False
        self._bytes -= entry.nbytes
        return True

    def corrupt(self, tag: str, kind: str, key: bytes) -> None:
        """Deterministic corruption hook for the ``serving.spill`` /
        ``serving.restore`` fault sites' ``poison`` action: flip one
        byte of the stored payload WITHOUT updating the digest, so the
        next ``fetch`` must detect it. A no-op on a missing key (the
        fault can race a host eviction)."""
        entry = self._entries.get((tag, kind, key))
        if entry is None or not entry.arrays:
            return
        a = entry.arrays[0]
        flat = np.frombuffer(a.tobytes(), np.uint8).copy()
        if flat.size == 0:
            return
        flat[0] ^= 0xFF
        entry.arrays[0] = np.frombuffer(flat.tobytes(),
                                        a.dtype).reshape(a.shape)

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0
