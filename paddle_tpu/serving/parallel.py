"""Tensor-parallel serving: one engine spanning a TP mesh via shard_map.

``ServingEngine(tp=N)`` keeps the engine's central contract — exactly TWO
compiled programs, the ``[max_slots]`` decode step and the
``[max_slots, chunk]`` mixed step — and runs each as ONE ``shard_map``
program over the ``mp`` axis (Megatron-style head/column/row partitioning,
Shoeybi et al. 2019; the 2D inference layouts of Pope et al. 2022 reduce
to this on a 1D mp mesh). The division of labour:

===========================  =============================================
sharded (per-device)         replicated (host-side / every device)
===========================  =============================================
KV page payloads: the kv-    block tables, seq_lens, content hashes,
head dim of every page       prefix registration, refcounts, eviction —
(`kvh/tp` heads per shard;   ALL pool metadata. Sampling lanes (temps,
int8 scales shard the same   top_ps, seeds, counts). Logits after the
dim)                         final all_gather, so sampling runs once per
q/k/v, gate/up weights       shard on identical values and the
(column-parallel) and        ``fold_in(key, token_index)`` contract is
o/down weights (row-         untouched.
parallel); embed rows and
lm_head columns (vocab)
===========================  =============================================

Attention is fully head-local: the paged scatter, the Pallas paged kernel
and the shared GQA decode core all run per-shard unchanged (the GQA ratio
``h/kvh`` survives sharding because both split by ``tp``). Each
transformer block issues exactly ONE psum (after o_proj / down_proj), the
vocab-parallel embedding one psum, and the vocab-sharded logits one
all_gather — nothing ever gathers the KV pool
(``tools/profile_serving.py --tp`` asserts these counts on the jaxpr).

Because pool arrays and weights stay GLOBAL logical ``jax.Array``s with a
``NamedSharding`` (sharding is a layout property, not a shape change),
every host-side path — spill/restore, snapshot capture, prefix injection,
scrub/rewind/cow — is tp-agnostic: ``device_get`` gathers shards into the
HostTier payload format, so a tp=2 snapshot restores into a tp=1 engine
and vice versa (SERVING.md "Tensor-parallel serving").

CPU verification needs no chip: force a virtual multi-device platform with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the
``dryrun_multichip`` harness; tests/conftest.py does this for the whole
suite).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import mesh as mesh_lib
from ..core.compat import shard_map
from ..distributed.fleet.mp_layers import manual_mp_region
from .errors import TPConfigError

__all__ = ["TPContext", "validate_tp_config", "partition_devices",
           "collective_counts"]


def validate_tp_config(config, tp: int) -> None:
    """Reject un-shardable configs at construction time with a typed
    :class:`TPConfigError` instead of a shape crash inside the compiled
    step. Every dimension the TP layout splits must divide evenly."""
    if tp < 1:
        raise TPConfigError(f"tp must be >= 1, got {tp}")
    if tp == 1:
        return
    checks = (
        ("num_key_value_heads", "KV pool head dim"),
        ("num_attention_heads", "query heads"),
        ("vocab_size", "vocab-parallel embedding / lm_head"),
        ("intermediate_size", "column-parallel gate/up"),
    )
    for field, what in checks:
        val = getattr(config, field, None)
        if val is not None and val % tp:
            raise TPConfigError(
                f"{field}={val} is not divisible by tp={tp} ({what} "
                f"shards this dimension)")


def partition_devices(n_groups: int, tp: int, devices=None) -> list[list]:
    """Carve the device list into ``n_groups`` disjoint TP groups of
    ``tp`` devices each — a fleet replica IS a TP group, so a 2-replica
    tp=2 fleet on 4 devices is ``partition_devices(2, 2)`` feeding each
    slice to ``ServingEngine(tp=2, tp_devices=slice)``."""
    devs = list(devices) if devices is not None else list(jax.devices())
    need = n_groups * tp
    if len(devs) < need:
        raise TPConfigError(
            f"{n_groups} TP groups of {tp} need {need} devices, have "
            f"{len(devs)} (CPU: set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")
    return [devs[i * tp:(i + 1) * tp] for i in range(n_groups)]


def _trim(*entries) -> P:
    """PartitionSpec with trailing Nones dropped. jax normalizes shard_map
    output shardings this way, and jit's cache key compares specs
    structurally — an input placed with ``P(None, None, 'mp', None)`` vs a
    step output carrying ``P(None, None, 'mp')`` would retrace the step on
    its second call even though the layouts are identical. Trimming at the
    source keeps every pool array's sharding bit-stable across calls, so
    ``step_program_counts()`` stays pinned."""
    entries = list(entries)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


class TPContext:
    """Everything the engine needs to span a TP group: the mp mesh over
    its device slice, the weight/pool shardings, and the shard_map
    wrapper that turns a step body into ONE manual-mp program."""

    axis = "mp"

    def __init__(self, model, tp: int, devices=None):
        validate_tp_config(model.config, tp)
        devs = list(devices) if devices is not None else list(jax.devices())
        if len(devs) < tp:
            raise TPConfigError(
                f"tp={tp} needs {tp} devices, have {len(devs)} (CPU: set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={tp})")
        self.tp = int(tp)
        self.mesh = mesh_lib.make_mesh({self.axis: tp}, devices=devs[:tp])
        self.devices = devs[:tp]
        # weight specs from the model's creation-time PartitionSpecs: keep
        # the mp entries, null every other axis (the serving mesh has only
        # mp); state keys absent from spec_dict (buffers) are replicated
        self._specs = {}
        for name, spec in model.spec_dict().items():
            if spec is None:
                self._specs[name] = P()
            else:
                self._specs[name] = _trim(*[a if a == self.axis else None
                                            for a in spec])

    # -- shardings ---------------------------------------------------------

    def spec_for(self, name: str) -> P:
        return self._specs.get(name, P())

    def shard_state(self, state: dict) -> dict:
        """One-time placement of the weights/buffers onto the TP mesh
        (column/row/vocab layout per the creation-time specs)."""
        return {k: jax.device_put(v, NamedSharding(self.mesh, self.spec_for(k)))
                for k, v in state.items()}

    def kv_shardings(self):
        """(payload, scale) NamedShardings for pool arrays: pages and
        rows replicated, the kv-head dim split on mp — each shard owns
        ``kvh/tp`` heads of EVERY page, so all page metadata stays valid
        on every shard."""
        return (NamedSharding(self.mesh, _trim(None, None, self.axis, None)),
                NamedSharding(self.mesh, P(None, None, self.axis)))

    def _kv_entry(self, arr):
        if hasattr(arr, "q"):  # QuantizedKV: codes + per-(row, head) scales
            return type(arr)(_trim(None, None, self.axis, None),
                             P(None, None, self.axis))
        return _trim(None, None, self.axis, None)

    def pool_specs(self, pools):
        return [(self._kv_entry(pk), self._kv_entry(pv)) for pk, pv in pools]

    # -- step compilation --------------------------------------------------

    def compile_step(self, fn, state, pools, n_lanes: int, n_lead: int):
        """Wrap a step body ``fn(state, pools, *lanes) -> (*outs, pools)``
        into ONE jitted shard_map program over the mp axis.

        All host-built lanes (tokens, block tables, seq_lens, sampling
        params) go in replicated; the ``n_lead`` leading outputs (sampled
        tokens, finite masks, …) come out replicated — they are computed
        identically on every shard from the all-gathered logits, which is
        what keeps sampling and the fold_in contract single-program.
        ``check_vma=False`` skips the replication proof for exactly those
        outputs. The un-jitted shard_map callable is kept on the returned
        function as ``_tp_inner`` so the collective-count report
        (:func:`collective_counts`) can trace it."""
        ax = self.axis

        def body(state, pools, *lanes):
            with manual_mp_region(ax):
                return fn(state, pools, *lanes)

        in_specs = ({k: self.spec_for(k) for k in state},
                    self.pool_specs(pools), *([P()] * n_lanes))
        out_specs = (*([P()] * n_lead), self.pool_specs(pools))
        inner = shard_map(body, mesh=self.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
        step = jax.jit(inner)
        step._tp_inner = inner
        return step


# -- collective-count report ----------------------------------------------

_COLLECTIVES = ("psum", "all_gather", "all_to_all", "all_reduce",
                "reduce_scatter", "ppermute")


def _subjaxprs(v):
    if hasattr(v, "eqns"):          # Jaxpr
        yield v
    elif hasattr(v, "jaxpr"):       # ClosedJaxpr
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for item in v:
            yield from _subjaxprs(item)


def collective_counts(fn, *args) -> dict[str, int]:
    """Trace ``fn(*args)`` and count collective primitives, recursing into
    sub-jaxprs (shard_map/pjit/scan bodies). The TP contract audited by
    ``tools/profile_serving.py --tp``: a step program carries exactly
    ``2 * num_layers + 1`` psums (one per attention block, one per MLP
    block, one for the vocab-parallel embedding) and exactly 1 all_gather
    (the vocab-sharded logits) — never an all_gather of the KV pool."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    counts: dict[str, int] = {}

    def walk(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            for c in _COLLECTIVES:
                if name == c or name.startswith(c + "_") or name == c + "2":
                    counts[c] = counts.get(c, 0) + 1
                    break
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub)

    walk(jaxpr.jaxpr)
    return counts
