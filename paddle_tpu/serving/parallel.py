"""Tensor/pipeline-parallel serving: one engine spanning a pp×mp mesh
via shard_map.

``ServingEngine(tp=N)`` keeps the engine's central contract — exactly TWO
compiled programs, the ``[max_slots]`` decode step and the
``[max_slots, chunk]`` mixed step — and runs each as ONE ``shard_map``
program over the ``mp`` axis (Megatron-style head/column/row partitioning,
Shoeybi et al. 2019; the 2D inference layouts of Pope et al. 2022 reduce
to this on a 1D mp mesh). ``ServingEngine(pp=P, tp=N)`` adds the second
mesh axis: the stacked decoder layers shard along ``pp`` (embed + the
first ``L/pp`` layers with stage 0, lm_head + the last with stage P-1 —
``models/llama_pipe``'s layout), the KV pool stacks its per-layer pairs
into ONE ``[L, pages, ...]`` pair carved the same way, and each step is
STILL one ``jit(shard_map)`` over the full pp×mp mesh: stage handoff is a
``ppermute`` of the ``[slots, h]`` activation ring inside a ``lax.scan``
over pipeline ticks (:meth:`TPContext.staged_forward`), so
``step_program_counts()`` stays ``{decode: 1, mixed: 1}`` under churn —
no per-stage program zoo. The division of labour:

===========================  =============================================
sharded (per-device)         replicated (host-side / every device)
===========================  =============================================
KV page payloads: the kv-    block tables, seq_lens, content hashes,
head dim of every page       prefix registration, refcounts, eviction —
(`kvh/tp` heads per shard;   ALL pool metadata. Sampling lanes (temps,
int8 scales shard the same   top_ps, seeds, counts). Logits after the
dim)                         final all_gather, so sampling runs once per
q/k/v, gate/up weights       shard on identical values and the
(column-parallel) and        ``fold_in(key, token_index)`` contract is
o/down weights (row-         untouched.
parallel); embed rows and
lm_head columns (vocab)
===========================  =============================================

Attention is fully head-local: the paged scatter, the Pallas paged kernel
and the shared GQA decode core all run per-shard unchanged (the GQA ratio
``h/kvh`` survives sharding because both split by ``tp``). Each
transformer block issues exactly ONE psum (after o_proj / down_proj), the
vocab-parallel embedding one psum, and the vocab-sharded logits one
all_gather — nothing ever gathers the KV pool
(``tools/profile_serving.py --tp`` asserts these counts on the jaxpr).

Because pool arrays and weights stay GLOBAL logical ``jax.Array``s with a
``NamedSharding`` (sharding is a layout property, not a shape change),
every host-side path — spill/restore, snapshot capture, prefix injection,
scrub/rewind/cow — is tp-agnostic: ``device_get`` gathers shards into the
HostTier payload format, so a tp=2 snapshot restores into a tp=1 engine
and vice versa (SERVING.md "Tensor-parallel serving").

CPU verification needs no chip: force a virtual multi-device platform with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the
``dryrun_multichip`` harness; tests/conftest.py does this for the whole
suite).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import mesh as mesh_lib
from ..core.compat import shard_map
from ..distributed.fleet.mp_layers import manual_mp_region
from ..quantization.serving import QuantizedKV
from .errors import TPConfigError

__all__ = ["TPContext", "validate_tp_config", "partition_devices",
           "collective_counts"]


def validate_tp_config(config, tp: int, pp: int = 1) -> None:
    """Reject un-shardable configs at construction time with a typed
    :class:`TPConfigError` instead of a shape crash inside the compiled
    step. Every dimension the TP layout splits must divide evenly, and
    the decoder stack must carve into ``pp`` equal stages."""
    if tp < 1:
        raise TPConfigError(f"tp must be >= 1, got {tp}")
    if pp < 1:
        raise TPConfigError(f"pp must be >= 1, got {pp}")
    if pp > 1:
        layers = getattr(config, "num_hidden_layers", None)
        if layers is not None and layers % pp:
            raise TPConfigError(
                f"num_hidden_layers={layers} is not divisible by pp={pp} "
                f"(the stacked decoder shards {layers // pp or 1}+ layers "
                f"per stage; stages must be equal)")
    if tp == 1:
        return
    checks = (
        ("num_key_value_heads", "KV pool head dim"),
        ("num_attention_heads", "query heads"),
        ("vocab_size", "vocab-parallel embedding / lm_head"),
        ("intermediate_size", "column-parallel gate/up"),
    )
    for field, what in checks:
        val = getattr(config, field, None)
        if val is not None and val % tp:
            raise TPConfigError(
                f"{field}={val} is not divisible by tp={tp} ({what} "
                f"shards this dimension)")


def partition_devices(n_groups: int, pp: int, tp: int | None = None,
                      devices=None) -> list[list]:
    """Carve the device list into ``n_groups`` disjoint parallel groups
    — a fleet replica IS a pp×tp group. Two calling forms:

    - ``partition_devices(n, tp)`` (2 positional args, the original
      TP-only form): ``n`` groups of ``tp`` devices each;
    - ``partition_devices(n, pp, tp)``: ``n`` groups of ``pp * tp``
      devices each, every slice feeding
      ``ServingEngine(pp=pp, tp=tp, tp_devices=slice)`` (the TPContext
      folds the flat slice into its pp×mp mesh, pp-major).

    Groups are contiguous disjoint slices; asking for more devices than
    exist raises a typed :class:`TPConfigError` naming the XLA flag that
    fakes them on CPU."""
    if tp is None:
        pp, tp = 1, pp
    if pp < 1 or tp < 1:
        raise TPConfigError(f"pp and tp must be >= 1, got pp={pp} tp={tp}")
    devs = list(devices) if devices is not None else list(jax.devices())
    group = pp * tp
    need = n_groups * group
    if len(devs) < need:
        raise TPConfigError(
            f"{n_groups} groups of pp={pp} x tp={tp} need {need} devices, "
            f"have {len(devs)} (CPU: set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")
    return [devs[i * group:(i + 1) * group] for i in range(n_groups)]


def _trim(*entries) -> P:
    """PartitionSpec with trailing Nones dropped. jax normalizes shard_map
    output shardings this way, and jit's cache key compares specs
    structurally — an input placed with ``P(None, None, 'mp', None)`` vs a
    step output carrying ``P(None, None, 'mp')`` would retrace the step on
    its second call even though the layouts are identical. Trimming at the
    source keeps every pool array's sharding bit-stable across calls, so
    ``step_program_counts()`` stays pinned."""
    entries = list(entries)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _stack_entry(arr, j):
    """Slice layer ``j`` out of a stacked pool array (QuantizedKV slices
    codes AND scales — the pair travels together, same as _page_copy)."""
    if isinstance(arr, QuantizedKV):
        return QuantizedKV(arr.q[j], arr.scale[j])
    return arr[j]


def _stack_update(arr, j, new):
    """Write layer ``j``'s updated pool back into the stacked array."""
    if isinstance(arr, QuantizedKV):
        return QuantizedKV(arr.q.at[j].set(new.q),
                           arr.scale.at[j].set(new.scale))
    return arr.at[j].set(new)


class TPContext:
    """Everything the engine needs to span a pp×tp group: the mesh over
    its device slice, the weight/pool shardings, and the shard_map
    wrapper that turns a step body into ONE manual-mp program. At
    ``pp=1`` this is exactly the original TP context (1-D mp mesh);
    ``pp>1`` adds the leading pipeline axis, stacks the decoder-layer
    state along it, and provides :meth:`staged_forward` — the in-program
    ppermute ring the pp step bodies are built from."""

    axis = "mp"
    pp_axis = "pp"

    #: staged-state key marker: ``model.layers.*.self_attn.q_proj.weight``
    #: names the [L, ...] stack of every layer's ``q_proj.weight``
    STACK = "*"

    def __init__(self, model, tp: int, devices=None, pp: int = 1):
        validate_tp_config(model.config, tp, pp)
        self.tp = int(tp)
        self.pp = int(pp)
        need = self.tp * self.pp
        devs = list(devices) if devices is not None else list(jax.devices())
        if len(devs) < need:
            raise TPConfigError(
                f"pp={pp} x tp={tp} needs {need} devices, have {len(devs)} "
                f"(CPU: set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={need})")
        if self.pp > 1:
            # pp-major device folding: stage i gets devs[i*tp:(i+1)*tp],
            # so a partition_devices slice maps stages contiguously
            self.mesh = mesh_lib.make_mesh(
                {self.pp_axis: self.pp, self.axis: self.tp},
                devices=devs[:need])
        else:
            self.mesh = mesh_lib.make_mesh({self.axis: tp},
                                           devices=devs[:tp])
        self.devices = devs[:need]
        # weight specs from the model's creation-time PartitionSpecs: keep
        # the mp entries, null every other axis (the serving mesh has only
        # mp beside pp); state keys absent from spec_dict (buffers) are
        # replicated
        self._specs = {}
        for name, spec in model.spec_dict().items():
            if spec is None:
                self._specs[name] = P()
            else:
                self._specs[name] = _trim(*[a if a == self.axis else None
                                            for a in spec])
        if self.pp > 1:
            self._init_pp(model)

    def _init_pp(self, model) -> None:
        """Pipeline-stage metadata from the model's ``pp_parts``
        decomposition: the stacked-layer key prefix, a template layer
        whose functional_call consumes one stacked slice, and the
        embed/head closures that reproduce the model's forward bitwise
        from a staged state dict."""
        parts = getattr(model, "pp_parts", None)
        if parts is None:
            raise TPConfigError(
                f"pp={self.pp} needs a model exposing pp_parts() "
                f"(the embed/layers/head decomposition); "
                f"{type(model).__name__} does not")
        parts = parts()
        self._pp_prefix = parts["layer_prefix"]
        self._pp_layers = int(parts["num_layers"])
        self._pp_template = parts["template"]
        self._pp_embed = parts["embed"]
        self._pp_head = parts["head"]
        self._pp_rope = tuple(parts["rope_keys"])
        # stacked-state specs: layer 0's mp spec with the pp axis
        # prepended on the new leading (layer) dim
        pre0 = f"{self._pp_prefix}0."
        self._pp_rel_keys = []
        for name in list(self._specs):
            if name.startswith(pre0):
                rel = name[len(pre0):]
                self._pp_rel_keys.append(rel)
                self._specs[self._stack_key(rel)] = _trim(
                    self.pp_axis, *self._specs[name])

    def _stack_key(self, rel: str) -> str:
        return f"{self._pp_prefix}{self.STACK}.{rel}"

    def stage_state(self, state: dict) -> dict:
        """Convert a flat model state dict into the staged pp layout:
        every per-layer key ``model.layers.<i>.<rel>`` folds into ONE
        stacked ``model.layers.*.<rel>`` array of shape ``[L, ...]``
        (sharded ``P('pp', ...)`` — stage s holds layers
        ``[s*L/pp, (s+1)*L/pp)``, llama_pipe's contiguous-stage layout);
        everything else (embed, final norm, lm_head, rope caches) keeps
        its key and replicates across pp."""
        staged: dict = {}
        layers: dict[str, dict[int, object]] = {}
        pre = self._pp_prefix
        for k, v in state.items():
            if k.startswith(pre):
                idx, rel = k[len(pre):].split(".", 1)
                layers.setdefault(rel, {})[int(idx)] = v
            else:
                staged[k] = v
        for rel, by_idx in layers.items():
            staged[self._stack_key(rel)] = jnp.stack(
                [by_idx[i] for i in range(self._pp_layers)])
        return staged

    # -- shardings ---------------------------------------------------------

    def spec_for(self, name: str) -> P:
        return self._specs.get(name, P())

    def shard_state(self, state: dict) -> dict:
        """One-time placement of the weights/buffers onto the mesh
        (column/row/vocab layout per the creation-time specs; stacked
        layer keys additionally split their leading layer dim on pp).
        A pp>1 engine stages the state first (:meth:`stage_state`)."""
        return {k: jax.device_put(v, NamedSharding(self.mesh, self.spec_for(k)))
                for k, v in state.items()}

    def kv_shardings(self):
        """(payload, scale) NamedShardings for pool arrays: pages and
        rows replicated, the kv-head dim split on mp — each shard owns
        ``kvh/tp`` heads of EVERY page, so all page metadata stays valid
        on every shard. At pp>1 the pool is ONE stacked
        ``[L, pages, ...]`` pair and the leading layer dim splits on pp
        — each stage's pool holds only its own layers' pages, so HBM
        per chip drops ~1/pp."""
        if self.pp > 1:
            spec = self._pp_pool_spec()
            return (NamedSharding(self.mesh, spec),
                    NamedSharding(self.mesh, spec))
        return (NamedSharding(self.mesh, _trim(None, None, self.axis, None)),
                NamedSharding(self.mesh, P(None, None, self.axis)))

    def _pp_pool_spec(self) -> P:
        """Canonical spec of the stacked pool. A size-1 mp axis (pp>1
        with tp=1) is dropped along with trailing Nones — jax
        canonicalizes output shardings exactly this way, and the device
        placement must match so the pool arrays a step program RETURNS
        hash to the same jit cache key as the ones a restore device_puts
        (else the first post-restore decode would retrace)."""
        return _trim(self.pp_axis, None, None,
                     self.axis if self.tp > 1 else None)

    def _kv_entry(self, arr):
        if self.pp > 1:
            spec = self._pp_pool_spec()
            if hasattr(arr, "q"):
                return type(arr)(spec, spec)
            return spec
        if hasattr(arr, "q"):  # QuantizedKV: codes + per-(row, head) scales
            return type(arr)(_trim(None, None, self.axis, None),
                             P(None, None, self.axis))
        return _trim(None, None, self.axis, None)

    def pool_specs(self, pools):
        return [(self._kv_entry(pk), self._kv_entry(pv)) for pk, pv in pools]

    # -- the staged (pipeline) forward ------------------------------------

    def staged_forward(self, state, pools, toks, tables, seq_lens, active,
                       n_live, waves: int = 1):
        """The pp step bodies' forward: embed the full ``[S, K]`` chunk,
        ring the activations through the staged decoder, return
        replicated ``[S, K, V]`` logits plus the updated stacked pool.
        Runs INSIDE the one shard_map body (manual-mp region active), so
        the whole pipeline — fill, drain, every wave — is a single
        compiled program no matter how requests churn.

        The ring is ``models/llama_pipe``'s GPipe schedule on the wave
        axis: the chunk splits into ``waves`` microbatches of
        ``Kw = K // waves`` rows, and a ``lax.scan`` over
        ``T = waves + pp - 1`` ticks runs wave ``w = t - r`` on stage
        ``r`` (validity-masked with ``jnp.where`` — never ``lax.cond``,
        collectives must run in SPMD lockstep), handing each tick's
        activations to stage ``r+1`` with ONE ``lax.ppermute``. Stage 0
        injects the wave's embedded rows; stage pp-1 banks its outputs.
        With ``waves == 1`` the schedule degrades to the naive
        sequential pipeline (1 busy stage per tick — the (pp-1)/pp
        bubble); ``waves == pp`` overlaps stages so the bubble shrinks
        to (pp-1)/(2pp-1).

        Masking keeps the math bitwise equal to the unstaged engine:
        invalid ticks run with ``active=False`` so every pool write
        lands on scratch page 0, per-wave lanes shift by the wave's row
        offset (``seq_lens + w*Kw``, ``clip(n_live - w*Kw, 0, Kw)``) so
        each row sees exactly the positions the full-chunk program gives
        it, and the final cross-stage broadcast is a psum of the
        last-stage outputs against zeros. Sampling runs AFTER the
        final-stage logits gather, replicated on every device — the
        ``fold_in(key, token_index)`` contract never sees the mesh."""
        from ..nn.module import functional_call
        pp = self.pp
        (pk, pv), = pools
        S, K = toks.shape
        W = int(waves)
        Kw = K // W
        emb = self._pp_embed(state, toks)              # [S, K, H]; 1 mp psum
        r = jax.lax.axis_index(self.pp_axis)
        is_first = r == 0
        is_last = r == pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        n_local = self._pp_layers // pp
        template = self._pp_template
        sliced = {rel: state[self._stack_key(rel)]
                  for rel in self._pp_rel_keys}

        def tick(carry, t):
            h, pk, pv, outs = carry
            w = t - r
            valid = (w >= 0) & (w < W)
            wc = jnp.clip(w, 0, W - 1)
            # stage 0 sources the wave from the embedded chunk; every
            # other stage consumes the ring input its predecessor
            # ppermuted at the end of the previous tick
            src = jax.lax.dynamic_slice_in_dim(emb, wc * Kw, Kw, axis=1)
            h = jnp.where(is_first, src, h)
            act_w = active & valid
            paged = (tables, seq_lens + wc * Kw, act_w)
            if n_live is not None:
                paged = paged + (jnp.clip(n_live - wc * Kw, 0, Kw),)
            for j in range(n_local):
                cache = (_stack_entry(pk, j), _stack_entry(pv, j))
                (h, (nk, nv)), _ = functional_call(
                    template, {rel: arr[j] for rel, arr in sliced.items()},
                    h, state[self._pp_rope[0]], state[self._pp_rope[1]],
                    None, cache, 0, paged, training=False)
                pk = _stack_update(pk, j, nk)
                pv = _stack_update(pv, j, nv)
            outs_new = jax.lax.dynamic_update_slice_in_dim(
                outs, h, wc * Kw, axis=1)
            outs = jnp.where(is_last & valid, outs_new, outs)
            h = jax.lax.ppermute(h, self.pp_axis, perm)
            return (h, pk, pv, outs), None

        carry0 = (jnp.zeros((S, Kw, emb.shape[-1]), emb.dtype), pk, pv,
                  jnp.zeros_like(emb))
        (h, pk, pv, outs), _ = jax.lax.scan(
            tick, carry0, jnp.arange(W + pp - 1))
        # ring close: broadcast the last stage's banked hidden states to
        # every stage (everyone else contributes exact zeros), then run
        # the replicated head — norm + lm_head + the one mp logits
        # gather — identically everywhere
        hidden = jax.lax.psum(
            jnp.where(is_last, outs, jnp.zeros_like(outs)), self.pp_axis)
        logits = self._pp_head(state, hidden)
        return logits, [(pk, pv)]

    # -- step compilation --------------------------------------------------

    def compile_step(self, fn, state, pools, n_lanes: int, n_lead: int):
        """Wrap a step body ``fn(state, pools, *lanes) -> (*outs, pools)``
        into ONE jitted shard_map program over the mp axis.

        All host-built lanes (tokens, block tables, seq_lens, sampling
        params) go in replicated; the ``n_lead`` leading outputs (sampled
        tokens, finite masks, …) come out replicated — they are computed
        identically on every shard from the all-gathered logits, which is
        what keeps sampling and the fold_in contract single-program.
        ``check_vma=False`` skips the replication proof for exactly those
        outputs. The un-jitted shard_map callable is kept on the returned
        function as ``_tp_inner`` so the collective-count report
        (:func:`collective_counts`) can trace it."""
        ax = self.axis

        def body(state, pools, *lanes):
            with manual_mp_region(ax):
                return fn(state, pools, *lanes)

        in_specs = ({k: self.spec_for(k) for k in state},
                    self.pool_specs(pools), *([P()] * n_lanes))
        out_specs = (*([P()] * n_lead), self.pool_specs(pools))
        inner = shard_map(body, mesh=self.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
        step = jax.jit(inner)
        step._tp_inner = inner
        return step


# -- collective-count report ----------------------------------------------

_COLLECTIVES = ("psum", "all_gather", "all_to_all", "all_reduce",
                "reduce_scatter", "ppermute")


def _subjaxprs(v):
    if hasattr(v, "eqns"):          # Jaxpr
        yield v
    elif hasattr(v, "jaxpr"):       # ClosedJaxpr
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for item in v:
            yield from _subjaxprs(item)


def collective_counts(fn, *args) -> dict[str, int]:
    """Trace ``fn(*args)`` and count collective primitives, recursing into
    sub-jaxprs (shard_map/pjit/scan bodies). The TP contract audited by
    ``tools/profile_serving.py --tp``: a step program carries exactly
    ``2 * num_layers + 1`` psums (one per attention block, one per MLP
    block, one for the vocab-parallel embedding) and exactly 1 all_gather
    (the vocab-sharded logits) — never an all_gather of the KV pool.

    Beside the plain per-primitive STATIC counts (``psum``, ``ppermute``,
    … — occurrences in the traced program, the original report), the dict
    carries two derived families the pp audit
    (``tools/profile_serving.py --pp``) pins:

    - ``"<prim>[<axis>]"`` — static count split by mesh axis, so the TP
      budget and the pipeline ring are separable: a pp×mp step shows
      ``psum[mp] == 2*L/pp + 1`` (each stage's layer blocks + the
      vocab-parallel embed) and ``psum[pp] == 1`` (the ring-close
      broadcast of the last stage's hidden states).
    - ``"<prim>_trips"`` / ``"<prim>_trips[<axis>]"`` — TRIP counts:
      static counts weighted by the ``lax.scan`` trip count(s) enclosing
      the primitive, i.e. how many times the collective actually runs
      per step. The one ppermute inside the pipeline scan is static 1
      but ``ppermute_trips[pp] == waves + pp - 1`` — exactly ``pp`` ring
      hops for the unwaved decode step (waves=1).
    """
    jaxpr = jax.make_jaxpr(fn)(*args)
    counts: dict[str, int] = {}

    def _axes(eqn):
        ax = eqn.params.get("axes")
        if ax is None:
            ax = eqn.params.get("axis_name")
        if ax is None:
            return ()
        if isinstance(ax, (tuple, list)):
            return tuple(str(a) for a in ax)
        return (str(ax),)

    def walk(jx, trips):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            for c in _COLLECTIVES:
                if name == c or name.startswith(c + "_") or name == c + "2":
                    counts[c] = counts.get(c, 0) + 1
                    tk = f"{c}_trips"
                    counts[tk] = counts.get(tk, 0) + trips
                    for a in _axes(eqn):
                        ak, atk = f"{c}[{a}]", f"{c}_trips[{a}]"
                        counts[ak] = counts.get(ak, 0) + 1
                        counts[atk] = counts.get(atk, 0) + trips
                    break
            inner = trips
            if name == "scan":
                inner = trips * int(eqn.params.get("length", 1))
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub, inner)

    walk(jaxpr.jaxpr, 1)
    return counts
