"""Deterministic serving traffic generator (ROADMAP item 5).

The staggered synthetic traces the bench configs used until now ("2 at
t=0, then 1 every 4 steps") cannot produce the regimes that actually
rank schedulers, cache tiers and admission policies: arrival bursts
that overflow the queue, Zipf-skewed tenant popularity that makes some
prefixes hot and others cold, and mixed prompt lengths that fragment
the pool. This module builds those traces as replayable data:

- **Arrivals** are counted per engine step (not wall seconds — the
  engine's only deterministic timebase) from a seeded generator:
  ``poisson`` draws a constant-rate Poisson count per step; ``bursty``
  modulates the rate with a deterministic on/off square wave (a
  Markov-modulated Poisson process with fixed phase lengths), the
  arrival shape that stresses queue depth and preemption.
- **Prompts** are ``system prefix + user suffix``: each request picks a
  tenant from a Zipf-popularity distribution over ``tenants`` distinct
  system prompts (tenant 0 hottest), then appends a fresh random suffix
  whose length is drawn from a weighted mixture of ranges. Shared
  system prompts are exactly what the prefix cache and the host tier
  monetize; the Zipf skew decides which of them stay warm.
- **Replay** is a pure function of the built trace: ``replay(target)``
  drives a :class:`~paddle_tpu.serving.engine.ServingEngine` or a
  :class:`~paddle_tpu.serving.fleet.FleetRouter` (duck-typed on
  ``submit``/``add_request``) step by step, submitting each request at
  its arrival step. Same ``Workload`` + same engine seed => bitwise
  identical streams, so A/B arms (tier off vs on) see IDENTICAL
  traffic and their goodput_at_slo / hit-rate deltas are attributable
  to the thing under test alone.

Everything derives from ``numpy.random.default_rng(seed)`` — no global
RNG state, no wall clock — so a Workload is a value: build it once,
replay it on every arm, ship its ``stats()`` in the bench summary.
"""

from __future__ import annotations

import bisect
import inspect
import math
from dataclasses import dataclass, field

import numpy as np

__all__ = ["Workload", "WorkloadRequest", "WorkloadSpec",
           "heavy_tail_workload", "long_prompt_workload", "make_workload",
           "overload_workload"]


@dataclass
class WorkloadRequest:
    """One trace entry: submit ``prompt`` at engine step
    ``arrival_step`` asking for ``max_new_tokens``. ``priority``
    (larger = more important) and ``deadline_s`` express the request's
    SLO class (SERVING.md "Overload control & tenant fairness") —
    replay forwards them to targets that accept them."""
    rid: str
    arrival_step: int
    prompt: list[int]
    max_new_tokens: int
    tenant: int
    priority: int = 0
    deadline_s: float | None = None


@dataclass
class WorkloadSpec:
    """Knobs for :func:`make_workload` (SERVING.md "KV tiering &
    traffic harness" documents each one).

    ``arrival`` is "poisson" or "bursty"; ``rate`` is mean arrivals per
    engine step. Bursty traffic alternates ``burst_on``-step windows at
    ``rate * burst_factor`` with ``burst_off``-step windows at
    ``rate * idle_factor``. ``prompt_mix`` is a weighted mixture of
    inclusive user-suffix length ranges; ``system_len`` is the range of
    per-tenant system-prompt lengths; ``zipf_alpha`` skews tenant
    popularity (tenant 0 hottest; larger alpha = hotter head)."""
    seed: int = 0
    n_requests: int = 32
    arrival: str = "poisson"
    rate: float = 0.5
    burst_on: int = 8
    burst_off: int = 24
    burst_factor: float = 4.0
    idle_factor: float = 0.0
    tenants: int = 4
    zipf_alpha: float = 1.2
    system_len: tuple[int, int] = (32, 64)
    prompt_mix: tuple = ((0.6, 8, 24), (0.3, 24, 64), (0.1, 64, 128))
    max_new: tuple[int, int] = (8, 32)
    vocab_size: int = 256
    eos_token_id: int | None = None
    # heavy-tailed suffix lengths (the chunked-prefill regime): with
    # ``suffix_dist="lognormal"``, a ``heavy_frac`` coin decides per
    # request between a LONG prompt — suffix length drawn from
    # lognormal(mu, sigma), clipped to ``suffix_clip`` — and the short
    # ``prompt_mix`` draw. Short requests optionally get their own
    # decode-heavy ``light_max_new`` range, so the trace interleaves
    # rare huge prefills with a steady stream of decode traffic —
    # exactly the mix where whole-prompt prefill stalls decode ITL.
    suffix_dist: str = "mixture"
    heavy_frac: float = 0.3
    lognormal_mu: float = 4.2
    lognormal_sigma: float = 0.8
    suffix_clip: tuple[int, int] = (48, 320)
    light_max_new: tuple[int, int] | None = None
    # SLO classes (the overload-control regime): per-tenant priority
    # (one int per tenant, larger = more important) and per-tenant
    # deadline distribution — each entry is None (no deadline), a
    # scalar seconds value, or an inclusive (lo, hi) uniform range
    # drawn per request. Both default off, so every existing trace
    # stays bitwise identical. Tenant 0 hot + LOW priority is the
    # canonical overload trace (:func:`overload_workload`).
    tenant_priorities: tuple | None = None
    tenant_deadlines: tuple | None = None


class Workload:
    """A built, replayable arrival trace (requests sorted by arrival
    step, FCFS within a step)."""

    def __init__(self, requests: list[WorkloadRequest],
                 spec: WorkloadSpec | None = None,
                 system_prompts: list[list[int]] | None = None):
        self.requests = sorted(requests,
                               key=lambda r: (r.arrival_step, r.rid))
        self.spec = spec
        self.system_prompts = system_prompts or []

    def __len__(self) -> int:
        return len(self.requests)

    def __iter__(self):
        return iter(self.requests)

    @property
    def horizon(self) -> int:
        """Last arrival step (replay keeps stepping past it until the
        target drains)."""
        return self.requests[-1].arrival_step if self.requests else 0

    def due(self, step: int) -> list[WorkloadRequest]:
        """Requests arriving exactly at ``step`` (pure — no cursor, so
        one Workload can drive any number of A/B arms)."""
        return [r for r in self.requests if r.arrival_step == step]

    def stats(self) -> dict:
        """Shape summary for bench reports: determinism means these
        describe every replay of this trace."""
        if not self.requests:
            return {"n_requests": 0}
        plens = [len(r.prompt) for r in self.requests]
        per_tenant: dict[int, int] = {}
        for r in self.requests:
            per_tenant[r.tenant] = per_tenant.get(r.tenant, 0) + 1
        return {
            "n_requests": len(self.requests),
            "arrival_span_steps": self.horizon + 1,
            "prompt_len_min": min(plens),
            "prompt_len_mean": sum(plens) / len(plens),
            "prompt_len_max": max(plens),
            "tenants": len(self.system_prompts),
            "tenant_counts": [per_tenant.get(t, 0)
                              for t in range(len(self.system_prompts))],
            "max_new_total": sum(r.max_new_tokens for r in self.requests),
        }

    def replay(self, target, max_steps: int | None = None,
               rid_prefix: str = "", retry_sheds: bool = True) -> dict:
        """Drive ``target`` (engine or fleet router) through the trace:
        at each step, submit the requests due, then ``target.step()``;
        keep stepping until the target drains. Backpressure rejections
        (typed ServingError subclasses with ``retryable`` set) are
        retried ONCE, deterministically: the request re-enqueues at
        ``step + max(1, ceil(retry_after_s))`` (1 when the error
        carries no hint), honouring the backoff the engine computed —
        so lossy-transport benches measure goodput, not shed luck. A
        request rejected again on its retry (or non-retryably) counts
        as shed, not raised — a traffic harness measures load shedding,
        it doesn't crash on it. ``retry_sheds=False`` restores the
        drop-on-first-shed behaviour. Returns ``{"steps", "submitted",
        "shed", "retried", "rids"}``."""
        from .errors import ServingError
        submit = getattr(target, "submit", None) or target.add_request
        has_work = (getattr(target, "has_work", None)
                    or target.scheduler.has_work)
        eos = self.spec.eos_token_id if self.spec is not None else None
        # forward tenant/priority/deadline_s only to targets whose
        # submit accepts them (signature probe, computed once) — a
        # scripted replay target without tenancy keeps working
        try:
            params = inspect.signature(submit).parameters
            slo_aware = ("tenant" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()))
        except (TypeError, ValueError):
            slo_aware = False
        i, step, shed, retried = 0, 0, 0, 0
        rids: list[str] = []
        deferred: list[tuple[int, object]] = []   # (due step, request)
        n = len(self.requests)

        def _submit_one(r, is_retry: bool) -> None:
            nonlocal shed, retried
            kw: dict = {}
            if slo_aware:
                kw["tenant"] = r.tenant
                kw["priority"] = r.priority
                if r.deadline_s is not None:
                    kw["deadline_s"] = r.deadline_s
            try:
                rids.append(submit(r.prompt, r.max_new_tokens,
                                   eos_token_id=eos,
                                   rid=rid_prefix + r.rid, **kw))
            except ServingError as e:
                if retry_sheds and not is_retry and e.retryable:
                    # single deterministic re-enqueue honouring the
                    # engine's own backoff hint (retry_after_s rides
                    # FleetOverloadedError / AdmissionShedError; errors
                    # without one wait the minimum one step)
                    hint = getattr(e, "retry_after_s", None) or 0.0
                    delay = max(1, math.ceil(hint))
                    bisect.insort(deferred, (step + delay, id(r), r))
                    retried += 1
                else:
                    shed += 1

        while i < n or deferred or has_work():
            while i < n and self.requests[i].arrival_step <= step:
                r = self.requests[i]
                i += 1
                _submit_one(r, is_retry=False)
            while deferred and deferred[0][0] <= step:
                _, _, r = deferred.pop(0)
                _submit_one(r, is_retry=True)
            target.step()
            step += 1
            if max_steps is not None and step >= max_steps:
                raise RuntimeError(
                    f"workload replay did not drain in {step} steps "
                    f"({n - i} unsubmitted, target still busy)")
        return {"steps": step, "submitted": len(rids), "shed": shed,
                "retried": retried, "rids": rids}


def _arrival_steps(spec: WorkloadSpec, rng) -> list[int]:
    """Per-step Poisson arrival counts, optionally rate-modulated by
    the deterministic on/off burst wave, until n_requests are placed."""
    steps: list[int] = []
    step = 0
    period = spec.burst_on + spec.burst_off
    while len(steps) < spec.n_requests:
        rate = spec.rate
        if spec.arrival == "bursty":
            in_burst = (step % period) < spec.burst_on
            rate = spec.rate * (spec.burst_factor if in_burst
                                else spec.idle_factor)
        k = int(rng.poisson(rate))
        for _ in range(min(k, spec.n_requests - len(steps))):
            steps.append(step)
        step += 1
        if step > 1000 * (spec.n_requests + 1):
            raise ValueError(
                f"arrival rate too low to place {spec.n_requests} "
                f"requests (arrival={spec.arrival!r}, rate={spec.rate}, "
                f"idle_factor={spec.idle_factor})")
    return steps


def make_workload(spec: WorkloadSpec | None = None, **kw) -> Workload:
    """Build a :class:`Workload` from a spec (or spec fields as
    kwargs). Fully deterministic in ``spec.seed``."""
    if spec is None:
        spec = WorkloadSpec(**kw)
    elif kw:
        raise TypeError("pass a WorkloadSpec OR field kwargs, not both")
    if spec.arrival not in ("poisson", "bursty"):
        raise ValueError(f"unknown arrival process {spec.arrival!r}")
    if spec.suffix_dist not in ("mixture", "lognormal"):
        raise ValueError(f"unknown suffix_dist {spec.suffix_dist!r}")
    if spec.tenants < 1:
        raise ValueError("tenants must be >= 1")
    if (spec.tenant_priorities is not None
            and len(spec.tenant_priorities) != spec.tenants):
        raise ValueError(
            f"tenant_priorities needs one entry per tenant "
            f"({len(spec.tenant_priorities)} != {spec.tenants})")
    if (spec.tenant_deadlines is not None
            and len(spec.tenant_deadlines) != spec.tenants):
        raise ValueError(
            f"tenant_deadlines needs one entry per tenant "
            f"({len(spec.tenant_deadlines)} != {spec.tenants})")
    rng = np.random.default_rng(spec.seed)
    # per-tenant system prompts (the shared prefixes): lengths first,
    # then token draws, all from the one seeded stream
    system_prompts: list[list[int]] = []
    for _ in range(spec.tenants):
        n = int(rng.integers(spec.system_len[0], spec.system_len[1] + 1))
        system_prompts.append(
            [int(t) for t in rng.integers(0, spec.vocab_size, size=n)])
    # Zipf tenant popularity: p(rank) ~ 1/(rank+1)^alpha, tenant 0 hottest
    ranks = np.arange(1, spec.tenants + 1, dtype=np.float64)
    probs = ranks ** -spec.zipf_alpha
    probs /= probs.sum()
    weights = np.asarray([w for w, _, _ in spec.prompt_mix], np.float64)
    weights /= weights.sum()
    arrivals = _arrival_steps(spec, rng)
    requests: list[WorkloadRequest] = []
    for i, arrival in enumerate(arrivals):
        tenant = int(rng.choice(spec.tenants, p=probs))
        heavy = (spec.suffix_dist == "lognormal"
                 and bool(rng.random() < spec.heavy_frac))
        if heavy:
            lo, hi = spec.suffix_clip
            sfx_len = int(np.clip(
                round(rng.lognormal(spec.lognormal_mu,
                                    spec.lognormal_sigma)), lo, hi))
        else:
            bucket = int(rng.choice(len(weights), p=weights))
            _, lo, hi = spec.prompt_mix[bucket]
            sfx_len = int(rng.integers(lo, hi + 1))
        suffix = [int(t) for t in rng.integers(0, spec.vocab_size,
                                               size=sfx_len)]
        mn = (spec.light_max_new
              if not heavy and spec.light_max_new is not None
              else spec.max_new)
        max_new = int(rng.integers(mn[0], mn[1] + 1))
        # SLO class: priority is a pure per-tenant lookup (no draw);
        # a deadline draw happens ONLY for tenants that have one, so
        # traces without SLO classes keep their exact draw order
        priority = (int(spec.tenant_priorities[tenant])
                    if spec.tenant_priorities is not None else 0)
        deadline: float | None = None
        if spec.tenant_deadlines is not None:
            d = spec.tenant_deadlines[tenant]
            if d is not None:
                try:
                    lo_d, hi_d = d
                    deadline = float(rng.uniform(lo_d, hi_d))
                except TypeError:
                    deadline = float(d)
        requests.append(WorkloadRequest(
            rid=f"wl-{i:04d}", arrival_step=arrival,
            prompt=system_prompts[tenant] + suffix,
            max_new_tokens=max_new, tenant=tenant,
            priority=priority, deadline_s=deadline))
    return Workload(requests, spec=spec, system_prompts=system_prompts)


def heavy_tail_workload(seed: int = 0, n_requests: int = 24,
                        **overrides) -> Workload:
    """The chunked-prefill stress preset: lognormal long prompts
    (~30% of requests, suffixes up to a few hundred tokens) interleaved
    with short decode-heavy traffic on small shared system prompts.
    Without chunking, each long prompt monopolizes an entire step and
    every decoding slot's inter-token latency eats the full prefill;
    with chunking the prompt streams through in budget-sized bites —
    this trace is what ``bench.py llama_serving_chunked`` and
    ``tools/profile_serving.py --chunked`` A/B over. Deterministic in
    ``seed``; any :class:`WorkloadSpec` field can be overridden."""
    kw: dict = dict(seed=seed, n_requests=n_requests,
                    arrival="poisson", rate=0.75,
                    tenants=2, zipf_alpha=1.2, system_len=(8, 16),
                    suffix_dist="lognormal", heavy_frac=0.3,
                    lognormal_mu=4.2, lognormal_sigma=0.8,
                    suffix_clip=(48, 320),
                    prompt_mix=((1.0, 4, 12),),
                    max_new=(4, 8), light_max_new=(16, 48))
    kw.update(overrides)
    return make_workload(WorkloadSpec(**kw))


def long_prompt_workload(seed: int = 0, n_requests: int = 16,
                         prompt_scale: float = 1.0,
                         **overrides) -> Workload:
    """The disaggregated-serving trace (ROADMAP item 1, SERVING.md
    "Disaggregated serving"): long-prompt-HEAVY Poisson arrivals over
    Zipf-shared system prompts — a lognormal prompt-length mixture
    where most requests (~70%) carry a LONG prompt and every request
    decodes a modest stream, the regime where prefill and decode fight
    hardest for the per-step budget even under chunking.
    ``prompt_scale`` is the 10x knob: it shifts the lognormal mu by
    ``ln(prompt_scale)`` and scales the clip range, so
    ``prompt_scale=10`` makes the same trace's prompts ~10x longer
    while arrivals, tenants and decode lengths stay fixed —
    ``bench.py llama_serving_disagg`` and ``tools/profile_serving.py
    --disagg`` sweep this knob to show colocated ITL degrading while
    the disaggregated arm stays flat. Deterministic in ``seed``; any
    :class:`WorkloadSpec` field can be overridden."""
    scale = float(prompt_scale)
    if scale <= 0.0:
        raise ValueError(f"prompt_scale must be > 0, got {prompt_scale}")
    kw: dict = dict(seed=seed, n_requests=n_requests,
                    arrival="poisson", rate=0.5,
                    tenants=2, zipf_alpha=1.2, system_len=(8, 16),
                    suffix_dist="lognormal", heavy_frac=0.7,
                    lognormal_mu=3.3 + math.log(scale),
                    lognormal_sigma=0.6,
                    suffix_clip=(max(8, int(round(16 * scale))),
                                 max(16, int(round(160 * scale)))),
                    prompt_mix=((1.0, 4, 12),),
                    max_new=(6, 12), light_max_new=(8, 16))
    kw.update(overrides)
    return make_workload(WorkloadSpec(**kw))


def overload_workload(seed: int = 0, n_requests: int = 48,
                      **overrides) -> Workload:
    """The canonical hot-tenant overload preset (SERVING.md "Overload
    control & tenant fairness"): tenant 0 is HOT (steep Zipf head,
    ~2/3 of all traffic) and LOW priority — the batch scraper flooding
    a shared fleet — while the cold tenants carry higher priorities,
    i.e. the interactive SLO classes a brownout must protect. Bursty
    arrivals overflow the queue during on-phases so admission quotas,
    fair scheduling and the brownout ladder all engage; FCFS collapses
    the cold tenants' TTFT on this trace, which is exactly what
    ``bench.py llama_serving_fairness`` A/Bs. Deadlines default OFF
    (pass ``tenant_deadlines=...`` to exercise infeasibility shedding
    on a virtual clock). Deterministic in ``seed``; any
    :class:`WorkloadSpec` field can be overridden."""
    kw: dict = dict(seed=seed, n_requests=n_requests,
                    arrival="bursty", rate=1.25,
                    burst_on=6, burst_off=10,
                    burst_factor=4.0, idle_factor=0.25,
                    tenants=4, zipf_alpha=2.5, system_len=(12, 20),
                    prompt_mix=((0.5, 8, 24), (0.35, 24, 64),
                                (0.15, 64, 96)),
                    max_new=(6, 16),
                    tenant_priorities=(0, 2, 2, 3))
    kw.update(overrides)
    return make_workload(WorkloadSpec(**kw))
