"""Crash-consistent snapshots of live serving-engine state.

The fleet (fleet.py) recovers a dead replica by deterministically
replaying every live request from token 0 — bitwise-correct, but the
recovery cost grows with context length, which is exactly wrong for
heavy-tailed long-prompt traffic. This module bounds it: every
``snapshot_interval`` engine steps the engine captures, per live
request, the minimal state that makes the request resumable —

- the request identity and sampling recipe (rid, prompt, seed,
  temperature/top_p/do_sample, max_new_tokens, eos, arrival order),
- the tokens generated so far and the materialized ``context_len``,
- the request's KV pages exported in the HostTier payload format
  (``[k0, v0, k1, v1, ...]``; int8 pools interleave codes and scales),

each payload guarded by a blake2b-128 digest and the metadata by its
own digest. Capture happens on the HOST side of the step via one
batched ``device_get`` — never inside a compiled program — so the
engine's no-retrace contract (``step_program_counts() == {"decode": 1,
"mixed": 1}``) is untouched. Under tensor parallelism that same
``device_get`` gathers the kv-head-sharded pool shards into the one
global payload format, which makes snapshots TP-PORTABLE: a tp=2
capture restores into a tp=1 engine and vice versa (the engine records
its ``tp`` degree in the snapshot meta for observability, not as a
compatibility key).

Two consumers:

1. **Bounded-replay failover** — on replica ejection the router asks
   the :class:`SnapshotStore` (shared across the fleet) for each live
   request's latest snapshot, restores the KV via
   ``KVCachePool.inject_prefix`` on the surviving replica and replays
   only the delta tokens since capture. The existing emitted-vs-
   produced dedup keeps client streams bitwise equal to a single-
   engine run; a corrupt or missing snapshot is digest-detected and
   falls back to full replay — never wrong tokens.

2. **Warm engine restart** — ``save_engine_snapshot`` /
   ``load_engine_snapshot`` persist the same records to disk through
   the PR 1 checkpoint commit protocol (stage into ``<path>.tmp``,
   write ``COMMIT``, rename), so a SIGKILLed process can come back
   with ``ServingEngine.restore(path)`` and continue every in-flight
   stream bitwise. A torn (uncommitted) directory is rejected with
   :class:`CheckpointCorruptionError`; a corrupted page payload is
   detected per-digest and only costs that request its zero-recompute
   restore, not its correctness.

Determinism makes the whole scheme cheap: a snapshot does NOT need the
RNG state or the decode logits — ``seed`` + token index reproduce every
sample, so the only expensive thing worth saving is the KV, and even
that is an optimisation (losing it costs recompute, never wrongness).
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass, field

import numpy as np

from ..distributed.checkpoint.save_load import (COMMIT_MARKER,
                                                CheckpointCorruptionError,
                                                _staging, is_committed)
from .tiering import _payload_digest

__all__ = ["RequestSnapshot", "SnapshotStore",
           "save_engine_snapshot", "load_engine_snapshot",
           "snapshot_to_wire", "snapshot_from_wire"]

_STATE_FILE = "state.json"
_PAGES_FILE = "pages.npz"


@dataclass
class RequestSnapshot:
    """Everything needed to resume one request bitwise, captured at a
    step boundary (so ``context_len`` tokens are materialized in the
    payload pages and position ``context_len`` onward is zeros)."""

    rid: object
    prompt: list
    max_new_tokens: int
    eos_token_id: object
    temperature: float
    top_p: float
    do_sample: bool
    seed: int
    arrival_seq: int
    tokens: list = field(default_factory=list)   # generated so far
    context_len: int = 0
    step: int = 0                                # engine step at capture
    kv_tag: str = ""                             # pool storage format
    page_size: int = 0
    payloads: list = field(default_factory=list)  # per-page HostTier format
    page_digests: list = field(default_factory=list)
    meta_digest: bytes = b""
    # multi-tenant LoRA (SERVING.md "Multi-tenant LoRA serving"): the
    # adapter digest (hex) the request decodes with, "" for base. The
    # restore side re-resolves it BEFORE re-admission — an adapter-bound
    # stream never silently resumes on base weights.
    adapter: str = ""

    # ---- integrity ----

    def _meta_bytes(self) -> bytes:
        rec = [str(self.rid), list(self.prompt), list(self.tokens),
               int(self.max_new_tokens),
               None if self.eos_token_id is None else int(self.eos_token_id),
               float(self.temperature), float(self.top_p),
               bool(self.do_sample), int(self.seed), int(self.arrival_seq),
               int(self.context_len), int(self.step), self.kv_tag,
               int(self.page_size)]
        if self.adapter:
            # appended only when set, so base-model snapshots sealed by
            # older builds keep verifying against the same digest
            rec.append(self.adapter)
        return json.dumps(rec).encode()

    def seal(self) -> "RequestSnapshot":
        """Compute the digests over the current content. Call once,
        right after capture — everything after that is verification."""
        self.page_digests = [_payload_digest(p) for p in self.payloads]
        self.meta_digest = _payload_digest([np.frombuffer(
            self._meta_bytes(), np.uint8)])
        return self

    def verify_meta(self) -> bool:
        return self.meta_digest == _payload_digest(
            [np.frombuffer(self._meta_bytes(), np.uint8)])

    def verify_payloads(self) -> bool:
        if len(self.page_digests) != len(self.payloads):
            return False
        return all(_payload_digest(p) == d
                   for p, d in zip(self.payloads, self.page_digests))

    def verify(self) -> bool:
        return self.verify_meta() and self.verify_payloads()

    # ---- derived ----

    def seq(self) -> list:
        """The materialized token sequence the payload pages hold —
        exactly ``context_len`` tokens of ``prompt + tokens`` (a
        decoding request's last generated token is sampled but not yet
        attended, hence the truncation)."""
        return (list(self.prompt) + list(self.tokens))[:self.context_len]

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for p in self.payloads for a in p)

    def corrupt(self) -> None:
        """Deterministic corruption hook for the ``serving.snapshot`` /
        ``serving.snapshot_restore`` fault sites' ``poison`` action:
        flip one byte WITHOUT updating the digests, so the next verify
        must detect it. Prefers the first payload array (exercising the
        page-digest ladder); a payload-less snapshot gets a flipped
        token so the meta digest trips instead."""
        if self.payloads and self.payloads[0]:
            a = self.payloads[0][0]
            flat = np.frombuffer(np.ascontiguousarray(a).tobytes(),
                                 np.uint8).copy()
            if flat.size == 0:
                return
            flat[0] ^= 0xFF
            self.payloads[0][0] = np.frombuffer(
                flat.tobytes(), a.dtype).reshape(a.shape)
        elif self.tokens:
            self.tokens[0] = int(self.tokens[0]) ^ 1
        else:
            self.prompt[0] = int(self.prompt[0]) ^ 1


class SnapshotStore:
    """In-memory latest-snapshot-per-request store, shared by every
    replica in a fleet (it models the off-replica durable medium — a
    replica's death must not take its requests' snapshots with it).
    ``get`` re-verifies digests so a snapshot corrupted after capture
    (bit rot, or the poison fault action) is dropped and counted, and
    the caller falls back to full replay."""

    def __init__(self):
        self._snaps: dict = {}
        self.counters: dict[str, int] = {
            "snapshots_captured": 0,     # capture rounds completed
            "snapshot_requests": 0,      # per-request snapshots stored
            "snapshot_pages": 0,         # pages exported, cumulative
            "snapshot_bytes": 0,         # payload bytes, cumulative
            "snapshot_failed": 0,        # captures dropped by a fault
            "snapshot_corrupt_detected": 0,
            "snapshot_hits": 0,
            "snapshot_misses": 0,
        }

    # ---- accounting ----

    @property
    def num_snapshots(self) -> int:
        return len(self._snaps)

    def stats(self) -> dict:
        return {"snapshot_live": len(self._snaps), **self.counters}

    @staticmethod
    def zero_stats() -> dict:
        """The ``stats()`` key set, all zero — what an engine WITHOUT
        snapshots reports, so the metrics schema never depends on
        whether snapshotting is enabled."""
        return {"snapshot_live": 0,
                "snapshots_captured": 0, "snapshot_requests": 0,
                "snapshot_pages": 0, "snapshot_bytes": 0,
                "snapshot_failed": 0, "snapshot_corrupt_detected": 0,
                "snapshot_hits": 0, "snapshot_misses": 0}

    # ---- the capture / restore surface ----

    def put(self, rid, snap: RequestSnapshot) -> None:
        """Store a request's latest snapshot (replacing any older one —
        failover only ever wants the newest verified state)."""
        self._snaps[rid] = snap
        self.counters["snapshot_requests"] += 1
        self.counters["snapshot_pages"] += len(snap.payloads)
        self.counters["snapshot_bytes"] += snap.nbytes

    def get(self, rid):
        """The request's latest snapshot, digest-re-verified, or None.
        A corrupt snapshot is dropped and counted — the caller falls
        back to full replay (wrong tokens are never worth a shortcut)."""
        snap = self._snaps.get(rid)
        if snap is None:
            self.counters["snapshot_misses"] += 1
            return None
        if not snap.verify():
            del self._snaps[rid]
            self.counters["snapshot_corrupt_detected"] += 1
            return None
        self.counters["snapshot_hits"] += 1
        return snap

    def drop(self, rid) -> None:
        """Forget a request (called when it finishes — the store is
        bounded by live requests, not by history)."""
        self._snaps.pop(rid, None)

    def rids(self) -> list:
        """The request ids currently holding snapshots — the transport's
        SNAPSHOT_FETCH enumeration (serving/transport.py). Sorted for a
        deterministic wire order."""
        return sorted(self._snaps)

    def corrupt(self, rid) -> None:
        """Poison hook for the fault sites: corrupt the stored snapshot
        in place (no-op on a missing rid — the fault can race a
        finish)."""
        snap = self._snaps.get(rid)
        if snap is not None:
            snap.corrupt()

    def clear(self) -> None:
        self._snaps.clear()


# ---- durable (warm-restart) persistence ----


def save_engine_snapshot(path: str, snaps: list, meta: dict | None = None
                         ) -> str:
    """Persist request snapshots through the checkpoint commit protocol
    (RESILIENCE.md): stage into ``<path>.tmp``, write ``state.json``
    (metadata + digests) and ``pages.npz`` (every payload array), then
    the ``COMMIT`` marker, then rename. A crash at any earlier point
    leaves a staging dir that ``load_engine_snapshot`` rejects."""
    stage = _staging(path)
    if os.path.exists(stage):
        shutil.rmtree(stage)
    os.makedirs(stage)
    records = []
    arrays = {}
    for i, s in enumerate(snaps):
        records.append({
            "rid": s.rid, "prompt": list(map(int, s.prompt)),
            "tokens": list(map(int, s.tokens)),
            "max_new_tokens": int(s.max_new_tokens),
            "eos_token_id": (None if s.eos_token_id is None
                             else int(s.eos_token_id)),
            "temperature": float(s.temperature), "top_p": float(s.top_p),
            "do_sample": bool(s.do_sample), "seed": int(s.seed),
            "arrival_seq": int(s.arrival_seq),
            "context_len": int(s.context_len), "step": int(s.step),
            "kv_tag": s.kv_tag, "page_size": int(s.page_size),
            "adapter": s.adapter,
            "pages": [len(p) for p in s.payloads],
            # npz cannot round-trip extension dtypes (bfloat16): store
            # each array as a raw uint8 view plus its dtype name, and
            # re-view on load — same bytes, so digests are unaffected
            "dtypes": [[str(np.asarray(a).dtype) for a in p]
                       for p in s.payloads],
            "page_digests": [d.hex() for d in s.page_digests],
            "meta_digest": s.meta_digest.hex(),
        })
        for j, payload in enumerate(s.payloads):
            for k, a in enumerate(payload):
                arrays[f"r{i}_p{j}_a{k}"] = \
                    np.ascontiguousarray(a).view(np.uint8)
    state = {"version": 1, "meta": meta or {}, "requests": records}
    with open(os.path.join(stage, _STATE_FILE), "w") as f:
        json.dump(state, f)
    np.savez(os.path.join(stage, _PAGES_FILE), **arrays)
    with open(os.path.join(stage, COMMIT_MARKER), "w") as f:
        f.write("ok\n")
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(stage, path)
    return path


def load_engine_snapshot(path: str):
    """Load a committed snapshot dir. Returns ``(snaps, meta)`` with
    snapshots ordered by arrival_seq (so re-admission preserves the
    original arrival order and therefore the scheduler's FCFS choices).

    The fallback ladder (RESILIENCE.md "Serving recovery playbook"):
    a torn / uncommitted / unreadable dir raises
    :class:`CheckpointCorruptionError` (there is nothing safe to
    resume); a request whose META digest fails also raises (identity
    bytes are unverifiable, resuming could emit wrong tokens); a
    request whose PAGE digest fails only loses its payloads — the
    snapshot degrades to meta-only and the engine recomputes that KV,
    still bitwise."""
    if not is_committed(path):
        raise CheckpointCorruptionError(
            f"serving snapshot at {path!r} is torn or uncommitted")
    try:
        with open(os.path.join(path, _STATE_FILE)) as f:
            state = json.load(f)
        npz = np.load(os.path.join(path, _PAGES_FILE))
    except (OSError, ValueError, json.JSONDecodeError) as e:
        raise CheckpointCorruptionError(
            f"serving snapshot at {path!r} is unreadable: {e}") from e
    snaps = []
    dropped_payloads = 0
    for i, rec in enumerate(state["requests"]):
        try:
            # NpzFile reads lazily — a bad CRC / short member surfaces
            # HERE, not at np.load; treat it like a failed page digest
            payloads = [[np.asarray(npz[f"r{i}_p{j}_a{k}"])
                         .view(np.dtype(rec["dtypes"][j][k]))
                         for k in range(n)]
                        for j, n in enumerate(rec["pages"])]
        except Exception:
            payloads = None
        s = RequestSnapshot(
            rid=rec["rid"], prompt=list(rec["prompt"]),
            max_new_tokens=rec["max_new_tokens"],
            eos_token_id=rec["eos_token_id"],
            temperature=rec["temperature"], top_p=rec["top_p"],
            do_sample=rec["do_sample"], seed=rec["seed"],
            arrival_seq=rec["arrival_seq"],
            tokens=list(rec["tokens"]), context_len=rec["context_len"],
            step=rec["step"], kv_tag=rec["kv_tag"],
            page_size=rec["page_size"], adapter=rec.get("adapter", ""),
            payloads=payloads or [],
            page_digests=[bytes.fromhex(d) for d in rec["page_digests"]],
            meta_digest=bytes.fromhex(rec["meta_digest"]))
        if not s.verify_meta():
            raise CheckpointCorruptionError(
                f"serving snapshot request {s.rid!r} failed metadata "
                f"digest verification")
        if payloads is None or not s.verify_payloads():
            # page bytes are damaged but the identity is intact: degrade
            # to meta-only (recompute path) rather than refusing resume
            s.payloads = []
            s.page_digests = []
            dropped_payloads += 1
        snaps.append(s)
    snaps.sort(key=lambda s: s.arrival_seq)
    meta = dict(state.get("meta") or {})
    meta["corrupt_payloads_dropped"] = dropped_payloads
    return snaps, meta


# ---- socket-wire serialization (serving/transport_socket.py) ----


def snapshot_to_wire(snap: RequestSnapshot) -> tuple[dict, bytes]:
    """Split a sealed snapshot into a JSON-able metadata dict and one
    contiguous payload blob for length-prefixed socket framing. The
    digests travel verbatim (hex) and are NOT recomputed on either
    side: the receiving transport's ``snap.verify()`` gate must see
    exactly the bytes the capturing engine sealed, so a byte flipped in
    flight fails verification instead of being silently re-blessed.
    Arrays cross as raw uint8 views with their dtype names recorded —
    the same bfloat16-safe convention as the durable npz form."""
    parts = []
    arrays = []
    for payload in snap.payloads:
        page = []
        for a in payload:
            raw = np.ascontiguousarray(a)
            page.append({"dtype": str(np.asarray(a).dtype),
                         "shape": list(np.asarray(a).shape),
                         "nbytes": int(raw.nbytes)})
            parts.append(raw.view(np.uint8).tobytes())
        arrays.append(page)
    meta = {
        "rid": snap.rid, "prompt": list(map(int, snap.prompt)),
        "tokens": list(map(int, snap.tokens)),
        "max_new_tokens": int(snap.max_new_tokens),
        "eos_token_id": (None if snap.eos_token_id is None
                         else int(snap.eos_token_id)),
        "temperature": float(snap.temperature),
        "top_p": float(snap.top_p),
        "do_sample": bool(snap.do_sample), "seed": int(snap.seed),
        "arrival_seq": int(snap.arrival_seq),
        "context_len": int(snap.context_len), "step": int(snap.step),
        "kv_tag": snap.kv_tag, "page_size": int(snap.page_size),
        "adapter": snap.adapter,
        "arrays": arrays,
        "page_digests": [d.hex() for d in snap.page_digests],
        "meta_digest": snap.meta_digest.hex(),
    }
    return meta, b"".join(parts)


def snapshot_from_wire(meta: dict, blob: bytes) -> RequestSnapshot:
    """Rebuild a :class:`RequestSnapshot` from its wire form — exactly
    as sent, including any in-flight damage: unlike the durable loader
    this never degrades or re-seals, so the caller's ``verify()`` is
    the arbiter of whether the bytes survived the trip."""
    payloads = []
    off = 0
    for page in meta["arrays"]:
        arrs = []
        for spec in page:
            n = int(spec["nbytes"])
            raw = np.frombuffer(blob[off:off + n], np.uint8).copy()
            off += n
            arrs.append(raw.view(np.dtype(spec["dtype"]))
                        .reshape(spec["shape"]))
        payloads.append(arrs)
    return RequestSnapshot(
        rid=meta["rid"], prompt=list(meta["prompt"]),
        max_new_tokens=meta["max_new_tokens"],
        eos_token_id=meta["eos_token_id"],
        temperature=meta["temperature"], top_p=meta["top_p"],
        do_sample=meta["do_sample"], seed=meta["seed"],
        arrival_seq=meta["arrival_seq"],
        tokens=list(meta["tokens"]), context_len=meta["context_len"],
        step=meta["step"], kv_tag=meta["kv_tag"],
        page_size=meta["page_size"], adapter=meta.get("adapter", ""),
        payloads=payloads,
        page_digests=[bytes.fromhex(d) for d in meta["page_digests"]],
        meta_digest=bytes.fromhex(meta["meta_digest"]))
