"""Typed failure surface of the serving engine.

Every way a request or the engine can fail maps to exactly one of these
(or to a terminal ``finish_reason`` on the request — see the "Serving
failure modes" table in SERVING.md). Nothing in ``paddle_tpu.serving``
fails with a bare RuntimeError or, worse, a silent busy loop: callers
can catch :class:`ServingError` and know they have seen every
engine-originated failure.

Every subclass carries a machine-readable ``retryable`` class attribute:
``True`` means the same request, submitted unchanged to a *different*
replica (or to the same engine later), can succeed — exactly the
decision a router front-end has to make per error. The in-process
router that acts on it is :class:`paddle_tpu.serving.fleet.FleetRouter`
(SERVING.md "Engine fleet & failover").

- :class:`QueueFullError` — backpressure: ``add_request`` refused
  because the bounded waiting queue is at ``max_queue_depth``.
  ``retryable``: the request is fine, this replica is busy — the fleet
  router retries it on a less-loaded replica (or sheds fleet-wide with
  :class:`FleetOverloadedError` when every replica is saturated).
- :class:`RequestTooLargeError` — the request could NEVER run: its
  prompt + decode budget needs more KV pages than the pool (or a slot)
  has. Rejected at add time — previously such a request silently spun
  in ``admit()`` forever. NOT retryable: every homogeneous replica
  would reject it identically.
- :class:`SchedulerStalledError` — the engine detected a zero-progress
  step (nothing admitted, nothing decoded, work still pending) and
  refuses to busy-loop. Carries a ``snapshot`` dict of the queue/pool
  state for the post-mortem. ``retryable`` — but only on ANOTHER
  replica: this engine's state cannot change on its own, so the fleet
  router ejects the replica and replays its in-flight requests
  elsewhere (deterministic replay, SERVING.md).
- :class:`EngineDrainingError` — ``add_request`` after ``drain()``
  began: the engine is shutting down; the fleet router routes around a
  draining replica automatically.
- :class:`FleetOverloadedError` — fleet-wide load shedding: the
  router's global queue is at capacity, meaning EVERY replica is
  saturated *and* the shared backlog is full. Retryable after backoff
  (clients should retry with jitter), but there is no other replica to
  try — this is the signal to scale out. Carries ``retry_after_s``, the
  router's drain-rate estimate of when capacity frees (RESILIENCE.md
  "Overload playbook").
- :class:`AdmissionShedError` — SLO-aware overload control
  (SERVING.md "Overload control & tenant fairness"): ``add_request``
  shed the request at admission because a per-tenant quota (live slots
  or queued tokens) is exhausted, or because its deadline is
  INFEASIBLE — the estimated queue wait + prefill + decode already
  exceeds the remaining ``deadline_s``, so running it would burn pool
  pages on a guaranteed timeout. Retryable after ``retry_after_s``
  (the engine's deterministic drain-rate estimate); ``kind`` says
  which gate fired (``tenant_quota`` / ``deadline_infeasible``).
- :class:`TPConfigError` — the model cannot be tensor-parallel-sharded
  at the requested degree (``kv_heads % tp``, ``vocab % tp``, … fail)
  or the mesh cannot be built (too few devices). Raised at
  ``ServingEngine(tp=N)`` construction instead of a shape crash inside
  the compiled step. NOT retryable: every replica of the same config
  would fail identically.
- :class:`TransportError` — a fleet wire message failed its blake2b
  digest re-verify at receive (``serving/transport.py``): the payload
  was corrupted in flight. The message is dropped and counted, never
  consumed; retryable — the sender's at-least-once retransmission
  delivers an intact copy.
- :class:`StaleEpochError` — epoch fencing (SERVING.md "Fleet
  transport & membership"): a message carried a replica epoch below
  the receiver's fence, i.e. a zombie replica returning from a
  partition tried to ack work the router already failed over. The
  message is discarded and counted; retryable only in the sense that
  the CURRENT epoch owns the request — the stale sender must never
  retry it.
- :class:`ReplicaSpawnError` — multi-host spawn/attach (SERVING.md
  "Multi-host serving"): a replica host process exited before
  connecting, or the fleet's connect barrier timed out. The fleet was
  never fully formed — nothing to fail over, nothing was accepted.
  Retryable: spawn again (a crashed child usually means a bad spec or
  an environment problem, which the carried exit status pinpoints).
"""

from __future__ import annotations

__all__ = ["ServingError", "QueueFullError", "RequestTooLargeError",
           "SchedulerStalledError", "EngineDrainingError",
           "FleetOverloadedError", "TPConfigError", "AdmissionShedError",
           "TransportError", "StaleEpochError", "ReplicaSpawnError"]


class ServingError(RuntimeError):
    """Base of every typed serving failure.

    ``retryable`` (class attribute, machine-readable): whether the SAME
    request can succeed if resubmitted — to another replica for
    engine-scoped failures, or after backoff for load shedding. The
    conservative base default is False; each subclass states its own.
    """

    retryable: bool = False


class QueueFullError(ServingError):
    """Bounded-queue backpressure: the waiting queue is at capacity.
    Retryable on another replica — ``fleet.FleetRouter`` does exactly
    that (least-loaded placement) instead of bouncing the client."""

    retryable = True


class RequestTooLargeError(ServingError, ValueError):
    """The request can never fit (prompt+decode pages exceed the pool
    or the per-slot table) — rejected at ``add`` instead of spinning.
    Not retryable: homogeneous replicas all reject it identically."""

    retryable = False


class SchedulerStalledError(ServingError):
    """A zero-progress engine step: work is pending but nothing can be
    admitted or decoded, and the state cannot change on its own.
    ``snapshot`` holds the queue/pool evidence. Retryable — on ANOTHER
    replica: the fleet router ejects the stalled engine and replays its
    in-flight requests deterministically elsewhere."""

    retryable = True  # on another replica, never on this one

    def __init__(self, msg: str, snapshot: dict | None = None):
        super().__init__(msg)
        self.snapshot = dict(snapshot or {})


class EngineDrainingError(ServingError):
    """``add_request`` called after ``drain()``: admission is closed.
    Retryable on another replica — the fleet router skips draining
    replicas at placement time."""

    retryable = True


class TPConfigError(ServingError, ValueError):
    """The model/mesh cannot support ``tp=N``: a sharded dimension
    (kv heads, attention heads, vocab, FFN width) is not divisible by
    the TP degree, or fewer than N devices are visible. Raised at
    engine construction — the compiled step never sees the bad shapes.
    Not retryable: homogeneous replicas all reject it identically."""

    retryable = False


class FleetOverloadedError(ServingError):
    """Fleet-wide load shedding (``fleet.FleetRouter.submit``): the
    router's global bounded queue is full, i.e. every healthy replica
    is saturated and the shared backlog on top of them is too. The
    request was not accepted anywhere. Retryable after client-side
    backoff; sustained occurrence means the fleet needs more replicas,
    not more retries. ``retry_after_s`` is the router's deterministic
    drain-rate estimate of when queue capacity frees — clients back
    off at least that long (plus jitter) before resubmitting."""

    retryable = True

    def __init__(self, msg: str, retry_after_s: float | None = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class AdmissionShedError(ServingError):
    """SLO-aware admission shed (``ServingEngine.add_request``): a
    per-tenant quota (live slots / queued tokens) is exhausted, or the
    request's deadline is infeasible given the current backlog — the
    estimated queue wait + prefill + decode time already exceeds
    ``deadline_s``, so admitting it would spend pool pages on a
    guaranteed timeout. Shed BEFORE any resources are held. Retryable
    after ``retry_after_s`` (the engine's drain-rate estimate, 0.0
    when no timing data exists yet); ``kind`` is ``"tenant_quota"`` or
    ``"deadline_infeasible"`` for client-side classification."""

    retryable = True

    def __init__(self, msg: str, retry_after_s: float = 0.0,
                 kind: str = "tenant_quota", tenant: int = 0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.kind = kind
        self.tenant = tenant


class TransportError(ServingError):
    """A fleet wire message failed its blake2b digest re-verify at
    receive (``serving/transport.py``): corrupted in flight. Dropped
    and counted (``corrupt_dropped``), never consumed. Retryable: the
    sender's at-least-once retransmission delivers an intact copy."""

    retryable = True


class StaleEpochError(ServingError):
    """Epoch fencing: the message's replica epoch is below the
    receiver's fence — a zombie replica back from a partition trying to
    ack work the router already failed over, or a fenced replica being
    handed zombie-epoch commands. Discarded and counted
    (``stale_epoch_discarded`` / ``fenced_dropped``); the CURRENT
    epoch owns the request."""

    retryable = True


class ReplicaSpawnError(ServingError):
    """Multi-host spawn/attach failed (``serving/replica_host.py`` /
    ``SocketTransport.wait_peers``): a replica host process died before
    saying HELLO, or the connect barrier timed out. Raised before any
    request is accepted — the fleet never formed, so there is no
    failover to attempt. Retryable: fix the spec/environment (the
    message carries the child's exit status) and spawn again."""

    retryable = True
