"""Typed failure surface of the serving engine.

Every way a request or the engine can fail maps to exactly one of these
(or to a terminal ``finish_reason`` on the request — see the "Serving
failure modes" table in SERVING.md). Nothing in ``paddle_tpu.serving``
fails with a bare RuntimeError or, worse, a silent busy loop: callers
can catch :class:`ServingError` and know they have seen every
engine-originated failure.

- :class:`QueueFullError` — backpressure: ``add_request`` refused
  because the bounded waiting queue is at ``max_queue_depth``. The
  caller should shed load or retry elsewhere.
- :class:`RequestTooLargeError` — the request could NEVER run: its
  prompt + decode budget needs more KV pages than the pool (or a slot)
  has. Rejected at add time — previously such a request silently spun
  in ``admit()`` forever.
- :class:`SchedulerStalledError` — the engine detected a zero-progress
  step (nothing admitted, nothing decoded, work still pending) and
  refuses to busy-loop. Carries a ``snapshot`` dict of the queue/pool
  state for the post-mortem.
- :class:`EngineDrainingError` — ``add_request`` after ``drain()``
  began: the engine is shutting down, retry on another replica.
"""

from __future__ import annotations

__all__ = ["ServingError", "QueueFullError", "RequestTooLargeError",
           "SchedulerStalledError", "EngineDrainingError"]


class ServingError(RuntimeError):
    """Base of every typed serving failure."""


class QueueFullError(ServingError):
    """Bounded-queue backpressure: the waiting queue is at capacity."""


class RequestTooLargeError(ServingError, ValueError):
    """The request can never fit (prompt+decode pages exceed the pool
    or the per-slot table) — rejected at ``add`` instead of spinning."""


class SchedulerStalledError(ServingError):
    """A zero-progress engine step: work is pending but nothing can be
    admitted or decoded, and the state cannot change on its own.
    ``snapshot`` holds the queue/pool evidence."""

    def __init__(self, msg: str, snapshot: dict | None = None):
        super().__init__(msg)
        self.snapshot = dict(snapshot or {})


class EngineDrainingError(ServingError):
    """``add_request`` called after ``drain()``: admission is closed."""
