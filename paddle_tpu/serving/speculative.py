"""Speculative decoding: draft proposers for the paged serving engine.

The engine's bandwidth wall is the weight stream: every decode step
reads all weight bytes to produce ONE token per slot. Speculative
decoding makes the same stream score k tokens per slot — a cheap
*drafter* guesses the next few tokens from request history, and the
engine's fixed-shape ``[max_slots, chunk]`` MIXED program (see
``engine._build_mixed_step``; verify rows share it with prefill
chunks) scores all draft positions at once, accepting the longest
prefix that matches what the engine would have sampled anyway.

The acceptance rule is sample-and-compare: at draft position n the
verify pass draws token ``t_n`` under the engine's standard sampling
contract (``fold_in(PRNGKey(seed), token_index)``, same temperature /
top-p / greedy switch as the 1-token decode step) and accepts the draft
iff it equals ``t_n``; the token actually emitted is ``t_n`` either
way. For the deterministic drafters here this IS the exact Leviathan
et al. accept/reject rule — a point-mass draft distribution accepts
with probability ``p(draft)`` and otherwise resamples from the
renormalized remainder, which is exactly what comparing against an
independent draw from ``p`` does. Two consequences the engine's tests
lean on:

- the emitted stream is **bitwise identical** to the non-speculative
  engine's (greedy and sampled) — drafts only change how many tokens a
  step emits, never which tokens;
- the stream is independent of the drafter entirely, so fleet failover
  replay stays bitwise even if a future drafter is adaptive or
  nondeterministic.

Drafters are pluggable via :class:`DraftProposer`; the built-in
:class:`NgramDrafter` is Saxena-style prompt lookup — no second model,
wins on shared-system-prompt and self-repetitive traffic, loses
(gracefully: zero drafts, plain 1-token steps) on text that never
repeats its own n-grams. A small draft *model* sharing the paged pool
can implement the same two-method interface later.
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["DraftProposer", "NgramDrafter", "SpeculativeConfig"]


class DraftProposer:
    """Interface a drafter implements.

    ``propose(req, k)`` returns up to ``k`` guessed continuation tokens
    for a running request (``req.prompt`` + ``req.tokens`` is the full
    visible history; the last element of ``req.tokens`` is the decode
    input the guesses extend). Returning ``[]`` is always legal and
    means "this step decodes normally". ``observe`` is called after
    every verify step with the proposal size and how many were
    accepted — adaptive drafters (or a draft model tuning its depth)
    hook here; the default is a no-op.

    Proposals may be wrong, stale, or random without affecting output
    correctness — the verify pass emits the engine's own sampled
    tokens regardless — so implementations only need to chase accept
    rate, never exactness.
    """

    def propose(self, req, k: int) -> list[int]:
        raise NotImplementedError

    def observe(self, req, n_draft: int, n_accepted: int) -> None:
        pass


class NgramDrafter(DraftProposer):
    """Prompt-lookup / n-gram drafter (Saxena 2023).

    Matches the last ``n`` tokens of the visible history (prompt +
    generated tokens) against every earlier position, longest ``n``
    first, rightmost (most recent) occurrence first, and proposes the
    tokens that followed that occurrence. Pure function of request
    history — deterministic across preemption recompute and fleet
    replay.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, req, k: int) -> list[int]:
        if k <= 0:
            return []
        ctx = list(req.prompt) + list(req.tokens)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if len(ctx) <= n:
                continue
            pat = ctx[-n:]
            # rightmost earlier occurrence of the trailing n-gram; the
            # match may not include the trailing position itself
            for i in range(len(ctx) - n - 1, -1, -1):
                if ctx[i:i + n] == pat:
                    cont = ctx[i + n:i + n + k]
                    if cont:
                        return [int(t) for t in cont]
                    break  # pattern only recurs flush at the end
        return []


@dataclass
class SpeculativeConfig:
    """Engine-facing speculative decoding switch.

    ``k`` is the verify step's row count per slot — 1 decode input plus
    up to ``k - 1`` draft tokens — and is a COMPILE-TIME shape: verify
    rows ride the engine's one ``[max_slots, chunk]`` mixed program
    (``chunk = max(prefill_chunk, k)``; SERVING.md "Chunked prefill &
    mixed steps"), and per-step draft counts pad into it (``n_live``
    masking), never retrace it. ``drafter`` overrides the built-in
    :class:`NgramDrafter` (constructed from ``max_ngram``/``min_ngram``
    otherwise).
    """

    k: int = 4
    max_ngram: int = 3
    min_ngram: int = 1
    drafter: DraftProposer | None = field(default=None, repr=False)

    def __post_init__(self):
        if self.k < 2:
            raise ValueError("speculative k must be >= 2 "
                             "(1 decode row + at least 1 draft row)")

    def make_drafter(self) -> DraftProposer:
        if self.drafter is not None:
            return self.drafter
        return NgramDrafter(self.max_ngram, self.min_ngram)
