"""Multi-tenant LoRA adapter serving: a paged adapter pool over one
base model (S-LoRA, Sheng et al. MLSys'24; Punica, Chen et al.
MLSys'24).

One base model stays resident; thousands of low-rank adapters page
through a fixed pool of HBM *adapter slots*, exactly the shape the KV
pool already built for pages (kv_cache.py): content-hash identity,
refcounts, LRU eviction of refcount-0 residents, and blake2b-digest-
verified spill/restore through the existing :class:`HostTier` payload
format (tiering.py, tag ``"lora"``).

The device layout is the gathered-batch form the engine's two compiled
programs consume: per projection target ``t`` one pair of buffers

    A[t]: [max_live, num_layers, in_dim,   max_rank]
    B[t]: [max_live, num_layers, max_rank, out_dim]

plus ``scales: [max_live] f32`` (= alpha/rank per slot). A request's
slot index selects its adapter through a ``[max_slots]`` adapter-table
array — an array VALUE, like a block table, so arbitrary adapter churn
never retraces (``step_program_counts()`` stays ``{decode: 1,
mixed: 1}``). Slot 0 is the reserved identity adapter: all-zero A/B
and scale 0, so a base-model request's delta is exactly zero.

Ranks below ``max_rank`` are zero-padded at load time; the padded
columns contribute exact zeros to the delta, so a rank-4 adapter in a
rank-8 pool computes the same values it would in a rank-4 pool.

Invariants (mirroring the KV pool's):
- the device buffers are allocated ONCE at construction and only ever
  updated with functional ``.at[]`` writes on the host-side load/evict
  paths — never inside a compiled program;
- slot 0 is never handed out and never written;
- a slot with refcount > 0 is never evicted or rewritten; refcount-0
  residents stay on an LRU and are reclaimed oldest-first;
- an adapter's identity is the blake2b-128 digest of its payload
  (weights + rank/alpha meta); the host tier re-verifies that digest
  at every fetch, so a corrupted spill can never load silently — the
  request fails typed (:class:`AdapterUnavailableError`), never with
  wrong tokens.
"""

from __future__ import annotations

from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

from .errors import ServingError
from .tiering import HostTier, _payload_digest

__all__ = ["LoRAAdapter", "AdapterPool", "AdapterExhaustedError",
           "AdapterUnavailableError", "llama_lora_targets"]


class AdapterExhaustedError(ServingError):
    """``acquire`` found no free slot and no evictable refcount-0
    resident: every live slot is pinned by a running request. The
    scheduler treats it like pool exhaustion — the request waits at
    the head of the queue until a running request releases its slot.
    Retryable by construction (capacity frees as requests finish)."""

    retryable = True


class AdapterUnavailableError(ServingError):
    """The adapter cannot be materialized here: it was never
    registered on this engine, or its host-tier payload was evicted
    or failed the blake2b digest re-verify (corruption is DETECTED,
    never served). Retryable on another replica that still holds an
    intact copy; never silently degraded to the base model."""

    retryable = True


def llama_lora_targets(config):
    """The seven projection targets of a Llama decoder layer as
    ``(name, in_dim, out_dim)`` triples — the classic full-target LoRA
    set (q/k/v/o + gate/up/down)."""
    h = config.num_attention_heads * config.head_dim
    kv = config.num_key_value_heads * config.head_dim
    hs, im = config.hidden_size, config.intermediate_size
    return (("q_proj", hs, h), ("k_proj", hs, kv), ("v_proj", hs, kv),
            ("o_proj", h, hs), ("gate_proj", hs, im), ("up_proj", hs, im),
            ("down_proj", im, hs))


class LoRAAdapter:
    """One adapter's host-side weights: per-target ``(A, B)`` numpy
    pairs, ``A: [num_layers, in_dim, rank]``, ``B: [num_layers, rank,
    out_dim]``, plus the classic ``alpha/rank`` scale. Identity is the
    blake2b-128 digest of the payload (weights + meta), computed once
    at construction — the content hash the pool keys slots by."""

    def __init__(self, name: str, params: dict, rank: int,
                 alpha: float | None = None):
        self.name = str(name)
        self.rank = int(rank)
        self.alpha = float(alpha if alpha is not None else rank)
        self.params = {t: (np.asarray(a, np.float32),
                           np.asarray(b, np.float32))
                       for t, (a, b) in params.items()}
        for t, (a, b) in self.params.items():
            if a.shape[-1] != self.rank or b.shape[-2] != self.rank:
                raise ValueError(
                    f"target {t}: A{a.shape}/B{b.shape} do not carry "
                    f"rank {self.rank}")
        self.digest = _payload_digest(self.payload())

    @classmethod
    def random(cls, name: str, config, rank: int = 4,
               alpha: float | None = None, seed: int = 0,
               scale: float = 0.02, targets=None) -> "LoRAAdapter":
        """Deterministic random adapter for tests/benchmarks (seeded
        numpy, never jax — host-side identity must not depend on the
        accelerator)."""
        rng = np.random.default_rng(seed)
        L = config.num_hidden_layers
        params = {}
        for t, din, dout in (targets or llama_lora_targets(config)):
            params[t] = (
                rng.standard_normal((L, din, rank)).astype(np.float32)
                * scale,
                rng.standard_normal((L, rank, dout)).astype(np.float32)
                * scale)
        return cls(name, params, rank, alpha)

    def payload(self) -> list:
        """HostTier payload form (tiering.py): a flat list of
        contiguous numpy arrays — one f32 meta row ``[rank, alpha]``
        followed by A, B per target in sorted-name order. The digest
        over this list IS the adapter's identity."""
        parts = [np.asarray([self.rank, self.alpha], np.float32)]
        for t in sorted(self.params):
            a, b = self.params[t]
            parts.append(np.ascontiguousarray(a))
            parts.append(np.ascontiguousarray(b))
        return parts

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes + b.nbytes for a, b in self.params.values())

    def merged_into(self, state: dict, prefix: str = "model.layers"):
        """Fold this adapter into a base-model state dict:
        ``W_eff = W + scale * (A @ B)`` per target per layer — the
        reference arm of the engine==merged-generate parity tests.
        Returns a NEW state dict (the input is not mutated)."""
        out = dict(state)
        s = self.alpha / self.rank
        for t, (a, b) in self.params.items():
            for li in range(a.shape[0]):
                sub = "self_attn" if t.endswith(("q_proj", "k_proj",
                                                "v_proj", "o_proj")) \
                    else "mlp"
                key = f"{prefix}.{li}.{sub}.{t}.weight"
                w = np.asarray(out[key], np.float32)
                out[key] = jnp.asarray(
                    w + s * (a[li] @ b[li]), out[key].dtype)
        return out


class AdapterPool:
    """Paged HBM pool of LoRA adapters behind one base model.

    ``max_live`` counts SLOTS including the reserved identity slot 0;
    ``max_rank`` is the padded rank every loaded adapter occupies.
    Registration parks the digest-verified payload in the host tier
    (tag ``"lora"``); ``acquire`` pages it into a slot on first use
    and refcounts it across requests; refcount-0 slots linger on an
    LRU and are evicted (spilled back if the tier lost the payload)
    only when a miss needs the slot."""

    def __init__(self, config, max_live: int = 8, max_rank: int = 8,
                 dtype=jnp.float32, host_tier=None, targets=None):
        if max_live < 2:
            raise ValueError("max_live must be >= 2 (slot 0 is the "
                             "reserved identity adapter)")
        self.config = config
        self.max_live = int(max_live)
        self.max_rank = int(max_rank)
        self.dtype = dtype
        self.targets = tuple(targets or llama_lora_targets(config))
        L = config.num_hidden_layers
        self.num_layers = L
        # gathered-batch device buffers, slot 0 = identity (all zero)
        self._A = {t: jnp.zeros((max_live, L, din, max_rank), dtype)
                   for t, din, dout in self.targets}
        self._B = {t: jnp.zeros((max_live, L, max_rank, dout), dtype)
                   for t, din, dout in self.targets}
        self._scales = jnp.zeros((max_live,), jnp.float32)
        if host_tier is None or host_tier is True:
            host_tier = HostTier()
        elif isinstance(host_tier, int) and not isinstance(host_tier, bool):
            host_tier = HostTier(max_bytes=host_tier)
        self.host_tier: HostTier = host_tier
        # slot accounting (host-side integers, mirrors KVCachePool)
        self._free = list(range(max_live - 1, 0, -1))
        self._ref: dict[int, int] = {}
        self._slot_key: dict[int, bytes] = {}
        self._key_slot: dict[bytes, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # digest -> {name, rank, alpha, nbytes}; name -> digest
        self._registry: dict[bytes, dict] = {}
        self._names: dict[str, bytes] = {}
        self._peak_live = 0
        self.fault_step: int | None = None
        self.fault_path: str | None = None
        self.counters: dict[str, int] = {
            "adapter_hits": 0, "adapter_misses": 0, "adapter_loads": 0,
            "adapter_evictions": 0, "adapter_spills": 0,
            "adapter_restore_corrupt": 0, "adapter_unavailable": 0,
            "lora_bytes_streamed": 0,
        }

    # ---- registration / identity ----

    def register(self, adapter: LoRAAdapter) -> str:
        """Park the adapter's digest-verified payload in the host tier
        and remember its meta; returns the hex content digest (the
        value requests pass as ``adapter=``). Re-registering identical
        content is a no-op returning the same digest."""
        key = adapter.digest
        if key not in self._registry:
            if not self.host_tier.put("lora", "full", key,
                                      adapter.payload()):
                raise AdapterUnavailableError(
                    f"adapter {adapter.name!r} ({adapter.nbytes} bytes) "
                    f"does not fit the host tier budget")
            self._registry[key] = {"name": adapter.name,
                                   "rank": adapter.rank,
                                   "alpha": adapter.alpha,
                                   "nbytes": adapter.nbytes}
        self._names[adapter.name] = key
        return key.hex()

    def resolve(self, ref) -> bytes:
        """Adapter reference -> content digest: accepts a registered
        name, a hex digest string, or raw digest bytes. Unknown refs
        fail typed at submission time, never at decode time."""
        if isinstance(ref, LoRAAdapter):
            ref = ref.digest
        if isinstance(ref, bytes):
            key = ref
        elif ref in self._names:
            key = self._names[ref]
        else:
            try:
                key = bytes.fromhex(ref)
            except (ValueError, TypeError):
                raise AdapterUnavailableError(
                    f"unknown adapter {ref!r}: not a registered name "
                    f"or digest") from None
        if key not in self._registry:
            raise AdapterUnavailableError(
                f"adapter {ref!r} is not registered on this engine")
        return key

    def resident(self, key: bytes) -> bool:
        """True when the adapter is HBM-resident right now (pinned or
        cached) — the fleet router's adapter-affinity signal."""
        return key in self._key_slot

    # ---- slot lifecycle ----

    def acquire(self, key: bytes) -> int:
        """Pin the adapter into a slot (loading it on a miss) and take
        a reference; returns the slot index for the adapter table.
        ``b""`` is the identity adapter: slot 0, no refcounting.
        Raises :class:`AdapterExhaustedError` when every slot is
        pinned, :class:`AdapterUnavailableError` when the payload is
        gone or corrupt (digest re-verify failed)."""
        if not key:
            return 0
        slot = self._key_slot.get(key)
        if slot is not None:
            r = self._ref.get(slot, 0)
            if r == 0:
                self._lru.pop(slot, None)
            self._ref[slot] = r + 1
            self.counters["adapter_hits"] += 1
            self._peak_live = max(self._peak_live, self.num_live)
            return slot
        self.counters["adapter_misses"] += 1
        if key not in self._registry:
            raise AdapterUnavailableError(
                f"adapter {key.hex()[:12]} is not registered here")
        if not self._free and not self._lru:
            raise AdapterExhaustedError(
                f"all {self.max_live - 1} adapter slots are pinned")
        # fault site ``serving.lora_fetch``: ``poison`` corrupts the
        # host-tier payload so the digest re-verify at fetch MUST catch
        # it; ``raise`` models a lost payload. Either way the request
        # fails typed — never a silent base-model fallback.
        from ..distributed import fault as _fault
        tier = self.host_tier
        try:
            _fault.trip("serving.lora_fetch", step=self.fault_step,
                        path=self.fault_path or key.hex(),
                        poison=lambda: tier.corrupt("lora", "full", key))
        except _fault.FaultInjected as e:
            self.counters["adapter_unavailable"] += 1
            raise AdapterUnavailableError(
                f"injected adapter-fetch fault: {e}") from e
        before = tier.counters["restore_corrupt_detected"]
        payload = tier.fetch("lora", "full", key)
        if payload is None:
            if tier.counters["restore_corrupt_detected"] > before:
                self.counters["adapter_restore_corrupt"] += 1
            self.counters["adapter_unavailable"] += 1
            raise AdapterUnavailableError(
                f"adapter {self._registry[key]['name']!r} payload is "
                f"missing or corrupt in the host tier")
        slot = self._free.pop() if self._free else self._evict_one()
        self._write_slot(slot, payload)
        nbytes = sum(a.nbytes for a in payload)
        tier.on_restored(nbytes)
        self.counters["adapter_loads"] += 1
        self.counters["lora_bytes_streamed"] += nbytes
        self._slot_key[slot] = key
        self._key_slot[key] = slot
        self._ref[slot] = 1
        self._peak_live = max(self._peak_live, self.num_live)
        return slot

    def release(self, slot: int) -> None:
        """Drop one reference; a refcount-0 slot stays resident on the
        LRU (a popular adapter's next request is a free hit)."""
        if slot == 0:
            return
        r = self._ref.get(slot, 0) - 1
        if r > 0:
            self._ref[slot] = r
            return
        self._ref.pop(slot, None)
        if slot in self._slot_key:
            self._lru[slot] = None
            self._lru.move_to_end(slot)

    def _evict_one(self) -> int:
        """Reclaim the LRU-oldest refcount-0 slot, spilling its payload
        back to the host tier first if the tier no longer holds it (the
        spill-before-deregister rule the KV pool follows)."""
        slot, _ = self._lru.popitem(last=False)
        key = self._slot_key.pop(slot)
        del self._key_slot[key]
        tier = self.host_tier
        if not tier.has("lora", "full", key):
            payload = self._slot_payload(slot, key)
            if tier.put("lora", "full", key, payload):
                self.counters["adapter_spills"] += 1
                self.counters["lora_bytes_streamed"] += sum(
                    a.nbytes for a in payload)
        self.counters["adapter_evictions"] += 1
        return slot

    # ---- device buffer I/O (host-side functional .at[] writes) ----

    def _write_slot(self, slot: int, payload: list) -> None:
        rank = int(round(float(payload[0][0])))
        alpha = float(payload[0][1])
        it = iter(payload[1:])
        per = {}
        for t in sorted(n for n, _, _ in self.targets):
            per[t] = (next(it), next(it))
        if rank > self.max_rank:
            raise AdapterUnavailableError(
                f"adapter rank {rank} exceeds the pool max_rank "
                f"{self.max_rank}")
        for t, din, dout in self.targets:
            a, b = per[t]
            a_pad = np.zeros((self.num_layers, din, self.max_rank),
                             np.float32)
            b_pad = np.zeros((self.num_layers, self.max_rank, dout),
                             np.float32)
            a_pad[:, :, :rank] = a
            b_pad[:, :rank, :] = b
            self._A[t] = self._A[t].at[slot].set(
                jnp.asarray(a_pad, self.dtype))
            self._B[t] = self._B[t].at[slot].set(
                jnp.asarray(b_pad, self.dtype))
        self._scales = self._scales.at[slot].set(alpha / rank)

    def _slot_payload(self, slot: int, key: bytes) -> list:
        """Rebuild the native-rank payload from the padded device slot
        (the spill path; bit-exact for f32 buffers because the pad
        columns are exact zeros and the slice drops them)."""
        meta = self._registry[key]
        rank, alpha = meta["rank"], meta["alpha"]
        parts = [np.asarray([rank, alpha], np.float32)]
        for t in sorted(n for n, _, _ in self.targets):
            a = np.asarray(self._A[t][slot], np.float32)[:, :, :rank]
            b = np.asarray(self._B[t][slot], np.float32)[:, :rank, :]
            parts.append(np.ascontiguousarray(a))
            parts.append(np.ascontiguousarray(b))
        return parts

    # ---- the compiled-program view ----

    def buffers(self):
        """The (params, scales) pytree the compiled steps consume:
        ``params[t] = (A[t], B[t])`` gathered-batch buffers + the
        per-slot scale row. Passed as ARGUMENTS every step — loads and
        evictions change values, never shapes, so the two compiled
        programs never retrace."""
        return ({t: (self._A[t], self._B[t]) for t, _, _ in self.targets},
                self._scales)

    def lora_ref(self, table) -> tuple:
        """A ready ``lora=`` argument for the model forward: the
        adapter table (any per-row slot list/array) bound to the
        current buffers."""
        params, scales = self.buffers()
        return (jnp.asarray(table, jnp.int32), params, scales)

    # ---- accounting ----

    @property
    def capacity(self) -> int:
        return self.max_live - 1

    @property
    def num_live(self) -> int:
        """Slots pinned by running requests (refcount > 0)."""
        return len(self._ref)

    @property
    def num_cached(self) -> int:
        return len(self._lru)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def utilization(self) -> float:
        return self.num_live / max(self.capacity, 1)

    def adapter_bytes_per_slot(self) -> int:
        """HBM bytes one loaded slot costs across all targets at the
        padded rank (the figure capacity planning multiplies by
        max_live)."""
        item = jnp.dtype(self.dtype).itemsize
        total = 0
        for t, din, dout in self.targets:
            total += self.num_layers * self.max_rank * (din + dout) * item
        return total

    def stats(self) -> dict:
        """Schema-stable gauge/counter dict, mirroring
        ``KVCachePool.stats()``; observability prefixes every key into
        the ``paddle_serving_lora_*`` family."""
        hits = self.counters["adapter_hits"]
        misses = self.counters["adapter_misses"]
        return {"max_live": self.max_live, "capacity": self.capacity,
                "max_rank": self.max_rank,
                "registered": len(self._registry),
                "resident": len(self._key_slot),
                "pinned": self.num_live, "cached": self.num_cached,
                "free": self.num_free,
                "utilization": self.utilization(),
                "peak_pinned": self._peak_live,
                "bytes_per_slot": self.adapter_bytes_per_slot(),
                "adapter_hit_rate": (hits / (hits + misses)
                                     if hits + misses else 0.0),
                **self.counters}

    @staticmethod
    def zero_stats() -> dict:
        """All-zero ``stats()`` schema (metrics merges it so the LoRA
        gauge family is schema-stable even before the first step)."""
        return {"max_live": 0, "capacity": 0, "max_rank": 0,
                "registered": 0, "resident": 0, "pinned": 0,
                "cached": 0, "free": 0, "utilization": 0.0,
                "peak_pinned": 0, "bytes_per_slot": 0,
                "adapter_hit_rate": 0.0,
                "adapter_hits": 0, "adapter_misses": 0,
                "adapter_loads": 0, "adapter_evictions": 0,
                "adapter_spills": 0, "adapter_restore_corrupt": 0,
                "adapter_unavailable": 0, "lora_bytes_streamed": 0}
