"""Iteration-level (continuous-batching) scheduler — Orca, OSDI '22.

FCFS with a per-step prefill token budget: every engine step the
scheduler first guarantees the running slots their next decode-write
page (preempting from the youngest when the pool is exhausted —
preempt-and-recompute, vLLM's recompute policy), then admits waiting
requests in strict arrival order while slots, pool pages and the token
budget allow. Requests therefore join and leave the running batch at
token granularity; nothing ever waits for a whole batch to drain.

With ``fair=True`` (SERVING.md "Overload control & tenant fairness"),
global strict-FCFS admission becomes a weighted token-deficit queue
ACROSS tenants — the virtual-token-counter fairness of Sheng et al.
("Fairness in Serving Large Language Models", OSDI '24): each tenant
carries a virtual counter of service tokens consumed (scaled by its
weight); admission always serves the backlogged tenant with the
smallest counter, and a tenant going idle never banks credit (its
counter is lifted to the backlogged minimum when it returns). FCFS
*within* a tenant is preserved, so every individual stream stays
bitwise identical to ``generate()`` — only inter-request ordering
changes, which the per-request ``fold_in(PRNGKey(seed), token_index)``
sampling contract is already immune to. Per-tenant admission quotas
(``tenant_max_live`` running slots, ``tenant_max_queued_tokens``
queued work) bound how much of the engine one tenant can hold; the
queued-token gate is enforced by the ENGINE at ``add_request`` (it
owns the retry_after_s estimate), the live-slot gate here at head
selection (a tenant at its cap is skipped, not errored — its turn
comes back when a slot frees).

All state here is host-side Python (deques and integer lists); the
device-side consequences (block tables, active masks, position offsets)
are materialized by the engine as plain array inputs to its single
compiled decode program. Under tensor parallelism (serving/parallel.py)
nothing here changes: scheduler state is REPLICATED host metadata — one
block table, one refcount ledger, one admission queue feed every shard
of the TP group, because each shard holds its slice of every page.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field

from ..observability.trace import NULL_TRACER
from .errors import QueueFullError, RequestTooLargeError
from .kv_cache import KVCachePool, PoolExhaustedError

__all__ = ["Request", "SamplingParams", "Scheduler",
           "WAITING", "RUNNING", "FINISHED", "PREEMPTED"]

WAITING = "waiting"
RUNNING = "running"
PREEMPTED = "preempted"
FINISHED = "finished"


@dataclass
class SamplingParams:
    """Per-request decode controls (each becomes a per-slot array lane in
    the compiled decode step — changing them never retraces)."""
    temperature: float = 1.0
    top_p: float = 1.0
    do_sample: bool = False  # False -> greedy argmax
    seed: int = 0


@dataclass
class Request:
    rid: str
    prompt: list[int]
    max_new_tokens: int
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_id: int | None = None

    # multi-tenant SLO classes (SERVING.md "Overload control & tenant
    # fairness"): tenant keys the fair-queue deficit counter and the
    # admission quotas; priority only ever decides WHICH queued request
    # a level-3 brownout sheds first (higher = more important) — neither
    # touches the compiled step or the emitted stream
    tenant: int = 0
    priority: int = 0

    # lifecycle
    state: str = WAITING
    arrival_seq: int = 0          # admission priority (FCFS tiebreak)
    tokens: list[int] = field(default_factory=list)   # generated so far
    finish_reason: str | None = None
    preemptions: int = 0

    # robustness (SERVING.md "Serving failure modes"): deadlines are
    # measured from arrival on the engine's injectable metrics clock and
    # enforced at step boundaries
    deadline_s: float | None = None        # arrival -> completion budget
    max_queue_wait_s: float | None = None  # arrival -> first admission
    arrival_t: float = 0.0                 # stamped by engine.add_request

    # cache bookkeeping (valid while RUNNING)
    slot: int | None = None
    pages: list[int] = field(default_factory=list)
    context_len: int = 0          # tokens currently materialized in cache
    # chunked prefill (SERVING.md "Chunked prefill & mixed steps"): the
    # materialization target of the CURRENT admission. A chunked admit
    # leaves context_len at the cached length and the engine streams the
    # suffix through the mixed step in budget-sized chunks, advancing
    # context_len until it reaches prefill_target; an unchunked admit
    # sets context_len = prefill_target in one shot (legacy behavior).
    prefill_target: int = 0
    # prefix-cache bookkeeping for the CURRENT admission: how many
    # leading tokens were served from cached pages (the engine prefills
    # only the suffix beyond them), and whether the last cached page was
    # a copy-on-write partial hit
    cached_len: int = 0
    cached_partial: bool = False
    # host-tier accounting for the CURRENT admission: how many of
    # cached_len tokens were restored from the host spill tier (they
    # skip recompute FLOPs but paid restore bytes — the scheduler
    # charged ceil(restored_len * restore_budget_frac) prefill-budget
    # tokens for them; SERVING.md "KV tiering & traffic harness")
    restored_len: int = 0
    # speculative decoding (serving/speculative.py): tokens the drafter
    # proposed for the NEXT step; the mixed program scores them at
    # positions context_len..context_len+len-1 and the engine clears the
    # list every step. Drafts never affect the emitted stream — only how
    # many tokens a step emits — so this is working state, not history.
    draft_tokens: list[int] = field(default_factory=list)
    # disaggregated serving (SERVING.md "Disaggregated serving"): a
    # prefill-only request stops at final-chunk completion — the engine
    # exports its KV to the handoff outbox instead of emitting the
    # first token, and finishes it with reason "handoff". The decode
    # side re-admits the handed-off request through the NORMAL path: a
    # fresh request whose full-prompt KV was injected matches
    # n_valid - 1 cached tokens (the cap above), so its admission
    # charges zero prefill-budget tokens beyond the one forced suffix
    # row that produces the first logits — bitwise identical to the
    # colocated final chunk.
    handoff: bool = False
    # multi-tenant LoRA (SERVING.md "Multi-tenant LoRA serving"): the
    # content digest (hex) of the adapter this request decodes with, ""
    # for the base model. adapter_slot is the AdapterPool slot pinned
    # for it while RUNNING (0 = the identity slot); acquired at admit,
    # released with the KV pages, so a preemption drops the pin but a
    # warm re-admit usually hits the pool's LRU cache.
    adapter: str = ""
    adapter_slot: int = 0

    @property
    def adapter_ns(self) -> bytes:
        """Prefix-cache namespace: adapters produce different KV for the
        same tokens, so cache identity is (adapter, tokens) — the digest
        bytes salt the pool's hash root (kv_cache._namespaced_root)."""
        return bytes.fromhex(self.adapter) if self.adapter else b""

    @property
    def recompute_len(self) -> int:
        """Prefill length on (re-)admission: the prompt plus all generated
        tokens except the last, which is the decode input (after a
        preemption the cache is rebuilt exactly to where it was)."""
        return len(self.prompt) + max(0, len(self.tokens) - 1)

    @property
    def prefilling(self) -> bool:
        """True while a RUNNING request still owes prefill chunks: its
        cache holds fewer tokens than this admission's target. A
        prefilling slot neither decodes nor drafts — it rides the mixed
        step's prefill lanes until context_len reaches the target."""
        return self.state == RUNNING and self.context_len < self.prefill_target

    @property
    def done(self) -> bool:
        return self.state == FINISHED


class Scheduler:
    def __init__(self, max_slots: int, prefill_token_budget: int = 2048,
                 max_queue_depth: int | None = None,
                 max_preemptions: int | None = None,
                 fair: bool = False,
                 tenant_weights: dict | None = None,
                 tenant_max_live: int | None = None,
                 tenant_max_queued_tokens: int | None = None):
        self.max_slots = max_slots
        self.prefill_token_budget = prefill_token_budget
        self.max_queue_depth = max_queue_depth
        self.max_preemptions = max_preemptions
        # tenant-aware fair scheduling + quotas (SERVING.md "Overload
        # control & tenant fairness"): fair=False keeps the strict
        # global FCFS this scheduler always had (the A/B baseline arm).
        # tenant_weights scales each tenant's virtual-token charge
        # (weight 2.0 = entitled to twice the service; default 1.0);
        # tenant_max_live caps RUNNING slots per tenant (enforced at
        # head selection); tenant_max_queued_tokens caps queued
        # prompt+decode tokens per tenant (enforced by the engine at
        # add_request, where the retry_after_s estimate lives).
        self.fair = bool(fair)
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_max_live = tenant_max_live
        self.tenant_max_queued_tokens = tenant_max_queued_tokens
        # the virtual token counters (Sheng et al., OSDI '24): service
        # tokens charged per tenant at admission, divided by the
        # tenant's weight — min-counter tenant is served next
        self._vtc: dict[int, float] = {}
        self.waiting: list[Request] = []   # kept sorted by arrival_seq
        self.running: dict[int, Request] = {}   # slot -> request
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self._arrival_counter = 0
        self.num_preemptions = 0
        # speculative decoding: the engine sets spec_k to its verify
        # step's row count (1 = plain decode). A verify step scores up
        # to spec_k tokens per running slot through the same weight
        # stream a prefill would use, so admission charges those extra
        # verify tokens against the SAME per-step prefill token budget —
        # one budget bounds the step's total token work.
        self.spec_k = 1
        # chunked prefill: when True (set by the engine), ``admit`` maps
        # pages and pins the cached prefix but leaves context_len at the
        # cached length — the engine streams the uncached suffix through
        # its mixed step in budget-metered chunks. The suffix then
        # charges the budget chunk by chunk AT DISPATCH, not at
        # admission, so admission only pays the host-tier restore toll.
        self.chunked = False
        # pipeline-parallel serving: the engine sets this to its mixed
        # step's microbatch wave count (pp when waving, else 1). The
        # engine's chunk planner wave-aligns non-final prefill bites to
        # multiples of the wave width chunk/pp_waves so a bite fills
        # whole waves instead of leaving the last wave half-empty — a
        # pacing hint only; chunk boundaries never change emitted
        # streams (the chunked-prefill parity contract).
        self.pp_waves = 1
        # multi-tenant LoRA: the engine points this at its AdapterPool
        # when lora serving is on. ``admit`` pins the head's adapter
        # slot alongside its KV pages; a request whose adapter payload
        # is lost/corrupt lands in ``admit_failures`` for the engine to
        # finish with a typed reason (never silently served base
        # weights), while pool-full exhaustion makes the head WAIT —
        # retryable, like any other resource.
        self.adapters = None
        self.admit_failures: list[Request] = []
        # injected by the engine when tracing is on. The scheduler owns
        # every queue/slot state transition, so it owns the request-track
        # lifecycle spans: "queued" opens at add/_requeue and closes at
        # admission (or terminal eviction from the queue); "running"
        # brackets slot occupancy exactly (_release closes it before any
        # requeue, keeping the track's begin/end stack balanced).
        self.tracer = NULL_TRACER

    # ---- queue ----

    def add(self, req: Request, pool: KVCachePool | None = None) -> None:
        """Enqueue a new request. With ``pool`` given, rejects requests
        that could NEVER run (prompt+decode pages beyond the pool's
        capacity) with :class:`RequestTooLargeError` — without this,
        ``admit()`` would spin on the queue head forever. A full bounded
        queue (``max_queue_depth``) rejects with
        :class:`QueueFullError` (backpressure, not an engine fault)."""
        if (self.max_queue_depth is not None
                and len(self.waiting) >= self.max_queue_depth):
            raise QueueFullError(
                f"waiting queue at max_queue_depth={self.max_queue_depth}; "
                f"request {req.rid!r} rejected (shed load or retry "
                f"elsewhere)")
        if pool is not None:
            need = pool.pages_for(len(req.prompt) + req.max_new_tokens)
            if need > pool.capacity:
                # prefix-cache accounting: only the UNCACHED suffix has
                # to be newly allocated — a prompt whose cached prefix
                # pages already sit in the pool can run even when its
                # total page count exceeds the capacity check above
                cached = 0
                if pool.cache_enabled:
                    cached = len(pool.match_prefix(
                        req.prompt, namespace=req.adapter_ns).full_pages)
                if need - cached > pool.capacity:
                    raise RequestTooLargeError(
                        f"request {req.rid!r} needs {need} pages for its "
                        f"prompt ({len(req.prompt)} tokens) + "
                        f"{req.max_new_tokens} decode tokens "
                        f"({cached} cached), but the pool has only "
                        f"{pool.capacity} allocatable pages — it "
                        f"could never run")
        if self.fair:
            # VTC lift (Sheng et al.): a tenant returning from idle is
            # lifted to the minimum counter of the currently-active
            # tenants, so idling never BANKS credit to burst with later
            # — fairness is over backlogged work, not history
            active = ({r.tenant for r in self.waiting}
                      | {r.tenant for r in self.running.values()})
            if req.tenant not in active and active:
                floor = min(self._vtc.get(t, 0.0) for t in active)
                self._vtc[req.tenant] = max(
                    self._vtc.get(req.tenant, 0.0), floor)
        req.arrival_seq = self._arrival_counter
        self._arrival_counter += 1
        req.state = WAITING
        self.waiting.append(req)
        self.tracer.begin("queued", track=req.rid,
                          prompt=len(req.prompt),
                          max_new=req.max_new_tokens)

    def _requeue(self, req: Request) -> None:
        """Put a preempted request back, keeping FCFS (arrival) order."""
        req.state = PREEMPTED
        keys = [r.arrival_seq for r in self.waiting]
        self.waiting.insert(bisect.bisect_left(keys, req.arrival_seq), req)
        self.tracer.begin("queued", track=req.rid,
                          preemptions=req.preemptions)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def live_requests(self) -> list[Request]:
        """Every non-terminal request (waiting + running), arrival order.
        This is what a fleet router fails over when it ejects the engine:
        each entry's rid/prompt/sampling is enough to replay it bitwise
        on another replica (SERVING.md "Engine fleet & failover")."""
        live = list(self.waiting) + list(self.running.values())
        return sorted(live, key=lambda r: r.arrival_seq)

    # ---- preemption ----

    def _preempt_youngest(self, pool: KVCachePool) -> Request:
        victim = max(self.running.values(), key=lambda r: r.arrival_seq)
        self._release(victim, pool)
        victim.preemptions += 1
        self.num_preemptions += 1
        self.tracer.instant("preempt", track=victim.rid,
                            preemptions=victim.preemptions)
        self.tracer.bump("preemptions")
        if (self.max_preemptions is not None
                and victim.preemptions > self.max_preemptions):
            # starvation guard: a request bounced out of the pool more
            # than max_preemptions times stops competing — it finishes
            # with a classified reason instead of thrashing recompute
            # prefills forever (the engine emits the terminal event)
            victim.state = FINISHED
            victim.finish_reason = "preempted_limit"
        else:
            self._requeue(victim)
        return victim

    def _release(self, req: Request, pool: KVCachePool,
                 register: bool = True) -> None:
        """Drop the request's slot and page REFERENCES (shared prefix
        pages may outlive it under other holders). With ``register``
        (every release except poison quarantine), its materialized
        prefix — full pages plus the frozen partial tail — is indexed
        first, so a preempted request's recompute, or a later request
        sharing the prompt, can map these pages instead of re-prefilling.

        A request released MID-PREFILL (context_len < prefill_target —
        a chunked prefill preempted between chunks) registers NOTHING:
        its later pages hold partially-written or zero content, and even
        the completed leading chunks are an unfinished admission —
        registration commits only on the final chunk (engine) or at a
        post-prefill release here. The page references are still
        dropped, so a mid-chunk preemption can never leak COW refs."""
        self.tracer.end("running", track=req.rid,
                        context_len=req.context_len)
        if register and req.pages and not req.prefilling:
            seq = (req.prompt + req.tokens)[:req.context_len]
            pool.register_prefix(seq, req.pages, include_partial=True,
                                 namespace=req.adapter_ns)
        pool.release(req.pages)
        if req.adapter_slot and self.adapters is not None:
            self.adapters.release(req.adapter_slot)
            req.adapter_slot = 0
        req.pages = []
        req.cached_len = 0
        req.cached_partial = False
        req.restored_len = 0
        req.draft_tokens = []   # drafts are per-step state; recompute
                                # re-proposes from the same history
        self._free_slots.append(req.slot)
        del self.running[req.slot]
        req.slot = None
        req.context_len = 0

    def finish(self, req: Request, pool: KVCachePool, reason: str) -> None:
        """Terminal transition from ANY live state: a running request
        releases its slot and pages; a waiting/preempted one just leaves
        the queue (deadline expiry and drain finish requests that never
        held resources). Poisoned/injected finishes never register their
        pages in the prefix index (the engine quarantined them already —
        registering NaN content would serve it to future hits)."""
        register = reason not in ("nonfinite", "injected")
        if req.slot is not None:
            self._release(req, pool, register=register)
        else:
            if req in self.waiting:
                self.waiting.remove(req)
                self.tracer.end("queued", track=req.rid)
            if req.pages:
                pool.release(req.pages)
                req.pages = []
        req.state = FINISHED
        req.finish_reason = reason

    # ---- the per-step scheduling decision ----

    def verify_token_reserve(self) -> int:
        """Verify tokens the next step may score beyond the plain
        one-per-slot decode: (spec_k - 1) draft rows per running slot.
        The engine subtracts this from the prefill budget it threads
        through ``admit`` so speculation and prefill bursts share one
        per-step token-work bound (0 when speculation is off). Slots
        still mid-prefill don't verify (they neither decode nor draft),
        so they don't reserve."""
        return (self.spec_k - 1) * sum(1 for r in self.running.values()
                                       if not r.prefilling)

    def ensure_decode_pages(self, pool: KVCachePool) -> list[Request]:
        """Before a decode step: every running request writes its next
        token at position context_len — make sure that page exists.
        Oldest requests are served first; when the pool is exhausted the
        youngest running request is preempted (possibly the one asking).
        Returns the requests preempted this call."""
        preempted: list[Request] = []
        for req in sorted(self.running.values(), key=lambda r: r.arrival_seq):
            if req.slot is None:  # lost its slot to an earlier preemption
                continue
            # a speculative step writes the decode token AND the drafts
            # optimistically, so the page guarantee covers all of them;
            # rejected drafts just leave (zeroed) headroom the request
            # would have grown into anyway
            needed = (pool.pages_for(req.context_len + 1
                                     + len(req.draft_tokens))
                      - len(req.pages))
            while needed > 0:
                try:
                    req.pages.extend(pool.alloc(needed))
                    needed = 0
                except PoolExhaustedError:
                    victim = self._preempt_youngest(pool)
                    preempted.append(victim)
                    if victim is req:
                        break  # it preempted itself; nothing left to grow
        return preempted

    # ---- tenant accounting (SERVING.md "Overload control & tenant
    # fairness") ----

    def live_slots(self, tenant: int) -> int:
        """RUNNING slots this tenant holds right now (the quantity
        ``tenant_max_live`` caps)."""
        return sum(1 for r in self.running.values() if r.tenant == tenant)

    def queued_tokens(self, tenant: int) -> int:
        """Queued service tokens (prompt + decode budget) this tenant
        holds in the waiting queue — what ``tenant_max_queued_tokens``
        caps at ``add_request`` (the engine raises the typed shed)."""
        return sum(max(r.recompute_len, 1) + r.max_new_tokens
                   for r in self.waiting if r.tenant == tenant)

    def _tenant_weight(self, tenant: int) -> float:
        w = float(self.tenant_weights.get(tenant, 1.0))
        return w if w > 0 else 1.0

    def _select_head(self) -> Request | None:
        """The next admission candidate. FCFS mode: the oldest waiting
        request (skipping tenants at their live-slot cap when quotas
        are on). Fair mode: the oldest waiting request OF the
        backlogged tenant with the smallest weighted virtual token
        counter — FCFS within the tenant, min-deficit across tenants;
        ties break by arrival for determinism. Returns None when every
        waiting request belongs to a tenant at its live cap."""
        cap = self.tenant_max_live
        if not self.fair:
            if cap is None:
                return self.waiting[0] if self.waiting else None
            for req in self.waiting:
                if self.live_slots(req.tenant) < cap:
                    return req
            return None
        best: Request | None = None
        best_key: tuple | None = None
        seen: set[int] = set()
        for req in self.waiting:   # arrival-sorted -> per-tenant FCFS head
            t = req.tenant
            if t in seen:
                continue
            seen.add(t)
            if cap is not None and self.live_slots(t) >= cap:
                continue
            key = (self._vtc.get(t, 0.0), req.arrival_seq)
            if best_key is None or key < best_key:
                best, best_key = req, key
        return best

    def admit(self, pool: KVCachePool, limit: int | None = None,
              budget: int | None = None,
              first: bool = True) -> list[Request]:
        """Admit waiting requests while a slot, the pool, and the
        per-step prefill token budget allow — in strict FCFS order by
        default, or fair-queue order across tenants with ``fair=True``
        (``_select_head``; FCFS within a tenant either way). Stops at
        the first selected head that does not fit (no queue jumping —
        the same head is re-selected next step, so it can never be
        starved by smaller requests behind it). Returns the admitted
        requests with slot + prompt pages assigned; the engine runs
        their prefills.

        The engine calls this with ``limit=1`` in a loop; ``budget``
        carries the remaining step budget across those calls and
        ``first=False`` says an admission already happened this step
        (the first admission of a step ignores the budget so an
        oversized prompt cannot deadlock). With ``chunked`` set the
        uncached suffix does NOT gate or charge admission — the engine
        meters it chunk by chunk at dispatch — so only the host-tier
        restore toll counts here, and the admitted request starts with
        ``context_len`` at its cached length and ``prefill_target`` at
        the full materialization goal."""
        admitted: list[Request] = []
        budget = self.prefill_token_budget if budget is None else budget
        while (self.waiting and self._free_slots
               and (limit is None or len(admitted) < limit)):
            req = self._select_head()
            if req is None:
                break  # every waiting tenant is at its live-slot cap
            n_valid = max(req.recompute_len, 1)
            # prefix-cache lookup: a fresh request caps the match at
            # n_valid - 1 (at least one suffix token must run through the
            # prefill program to produce its first logits); a recompute
            # (req.tokens non-empty — the prefill's prediction is
            # discarded anyway) may match fully and skip the program
            match = None
            cached = 0
            if pool.cache_enabled:
                cap = n_valid if req.tokens else n_valid - 1
                seq = req.prompt + req.tokens[:-1]
                match = pool.match_prefix(seq, max_tokens=cap,
                                          namespace=req.adapter_ns)
                # the optimistic (pre-restore) view: the whole cache
                # hierarchy hit, including host-tier tokens that still
                # have to be restored at commit time
                cached = match.total_cached
            suffix = n_valid - cached
            # only the UNCACHED suffix charges the prefill token budget
            # — plus the restore toll on host-tier tokens: they skip
            # recompute FLOPs but pay restore bytes, charged like a
            # partial cache hit at restore_budget_frac per token.
            # Chunked mode defers the suffix charge to chunk dispatch,
            # so only the restore toll gates admission here.
            charge = (0 if self.chunked else suffix) + pool.restore_charge(match)
            if (admitted or not first) and charge > budget:
                break
            n_new = (pool.pages_for(n_valid)
                     - (len(match.full_pages) if match else 0))
            if n_new > pool.num_available:
                break
            # multi-tenant LoRA: pin the head's adapter slot BEFORE any
            # pool mutation (the acquire may stream weights from the
            # host tier / evict an idle slot, but it never touches KV
            # pages, so a later rollback only has to release the pin).
            # Pool-full exhaustion makes the head WAIT like page
            # exhaustion; a lost/corrupt payload is terminal — the
            # request moves to admit_failures for the engine to finish
            # with a typed reason, and the NEXT head gets its turn.
            aslot = 0
            if req.adapter and self.adapters is not None:
                from .lora import (AdapterExhaustedError,
                                   AdapterUnavailableError)
                try:
                    aslot = self.adapters.acquire(req.adapter_ns)
                except AdapterExhaustedError:
                    break
                except AdapterUnavailableError:
                    self.waiting.remove(req)
                    self.tracer.end("queued", track=req.rid)
                    self.admit_failures.append(req)
                    continue
            # commit order matters: pin the matched pages FIRST so this
            # admission's own allocs (including restores) cannot
            # LRU-evict them, then restore the host-tier chain, then
            # allocate the suffix pages, then materialize the COW /
            # host-partial copy. Rollback on failure leaves the pool as
            # found — up to restored pages, which stay behind as
            # refcount-0 CACHED pages (warm for the retry).
            pinned: list[int] = []
            if match is not None and match.hit:
                pinned = list(match.full_pages)
                if match.partial_page is not None:
                    pinned.append(match.partial_page)
                pool.acquire(pinned)
            chain_pages: list[int] = []
            restored_tok = 0
            if match is not None and match.chain:
                chain_pages, restored_tok = pool.restore_chain(match)
            chain_ok = (match is None
                        or len(chain_pages) == len(match.chain))
            # the partial tail applies only after a fully-restored
            # chain (it continues the LAST chain page's content)
            use_hbm_partial = bool(chain_ok and match is not None
                                   and match.partial_page is not None)
            host_partial = None
            if (chain_ok and match is not None
                    and match.host_partial_key is not None):
                host_partial = pool.fetch_host_partial(match)
            # re-derive the ACTUAL cached length from what committed
            # (a failed restore shortens it; the difference recomputes)
            partial_q = 0
            if use_hbm_partial:
                partial_q = match.partial_len
            elif host_partial is not None:
                partial_q = match.host_partial_len
            if match is not None:
                cached = ((len(match.full_pages) + len(chain_pages))
                          * pool.page_size + partial_q)
                suffix = n_valid - cached
            n_new = (pool.pages_for(n_valid)
                     - (len(match.full_pages) if match else 0)
                     - len(chain_pages))
            try:
                pages = pool.alloc(n_new)
            except PoolExhaustedError:
                pool.release(pinned)
                pool.release(chain_pages)
                if aslot and self.adapters is not None:
                    self.adapters.release(aslot)
                self.tracer.instant("admit_rollback", track=req.rid,
                                    need=n_new,
                                    available=pool.num_available)
                self.tracer.bump("admit_rollbacks")
                break  # injected exhaustion (serving.alloc) — the head
                       # stays queued, never torn out of the FCFS order
            if match is not None and match.partial_page is not None:
                if use_hbm_partial:
                    # copy-at-map COW: the hitter gets a fresh page
                    # holding a copy of the cached partial page and
                    # extends THAT; the cached page itself is never
                    # written, then unpinned
                    pool.cow_into(match.partial_page, pages[0])
                pool.release([match.partial_page])
            elif host_partial is not None:
                # same COW rule, copy sourced from the host tier —
                # restored straight into the hitter's first suffix page
                pool.restore_partial_into(pages[0], host_partial)
                restored_tok += match.host_partial_len
            if match is not None:
                pool.count_match(match)
            self.waiting.remove(req)
            if self.fair:
                # charge the tenant's virtual token counter with the
                # service this admission buys (context to materialize +
                # decode budget), scaled by the tenant's weight — the
                # deficit that decides who is served next. Recomputes
                # after preemption charge again: they are real service.
                self._vtc[req.tenant] = (
                    self._vtc.get(req.tenant, 0.0)
                    + (n_valid + req.max_new_tokens)
                    / self._tenant_weight(req.tenant))
            req.pages = ((list(match.full_pages) if match else [])
                         + chain_pages + pages)
            req.cached_len = cached
            req.restored_len = restored_tok
            req.cached_partial = partial_q > 0
            req.adapter_slot = aslot
            req.slot = self._free_slots.pop()
            req.state = RUNNING
            req.prefill_target = n_valid
            # chunked: start at the cached length; the engine's mixed
            # step advances context_len chunk by chunk up to the target
            req.context_len = cached if self.chunked else n_valid
            self.running[req.slot] = req
            if self.tracer.enabled:
                self.tracer.end("queued", track=req.rid)
                self.tracer.instant("admit", track=req.rid, slot=req.slot,
                                    cached=cached, suffix=suffix,
                                    restored=restored_tok)
                self.tracer.begin("running", track=req.rid)
            admitted.append(req)
            if self.chunked:
                # the suffix charges at chunk dispatch; a prefilling slot
                # doesn't verify, so no (spec_k - 1) reserve either —
                # admission pays only the restore toll
                budget -= pool.restore_charge_tokens(restored_tok)
            else:
                # an admitted slot also joins this step's verify fan-out
                # (spec_k - 1 draft rows), charged like prefill tokens —
                # and restored tokens charge their restore toll
                budget -= (suffix + pool.restore_charge_tokens(restored_tok)
                           + (self.spec_k - 1))
        return admitted
