"""Paged KV-cache pool for the continuous-batching serving engine.

One fixed ``[num_pages, page_size, n_kv_heads, head_dim]`` array pair per
layer (the PagedAttention pool, SOSP '23); sequences own pages through
per-request int32 block tables instead of contiguous ``[B, max_len]``
buffers, so cache memory fragments at page granularity instead of
request granularity and a request's reservation grows one page at a
time as it decodes.

On top of the allocator sits **automatic prefix caching** (RadixAttention,
SGLang): pages are reference counted, full pages are indexed by a
chained content hash ``h_i = H(h_{i-1}, page_tokens_i)``, and a released
request's pages stay resident as refcount-0 *cached* pages on an LRU
instead of returning to the free list. A later request whose prompt
shares the prefix maps those pages straight into its block table
(``match_prefix`` + ``acquire``) and prefills only the uncached suffix.
Partially-filled last pages are indexed too and reused copy-on-write:
a hit never writes the cached page in place — the hitter receives a
fresh page holding a device copy (``cow_into``) and extends that.
``alloc`` evicts cached pages LRU-oldest only when the free list alone
cannot satisfy it, scrubbing them back to zero on the way out.

Invariants (relied on by the engine's no-retrace + determinism
contracts, SERVING.md):
- the device arrays are allocated ONCE at pool construction and only
  ever updated functionally inside the compiled prefill/decode programs
  — alloc/free/match move host-side integers, never device memory
  (the two exceptions, ``cow_into`` and scrub-on-evict, are single
  functional ``.at[]`` updates);
- page 0 is reserved as the scratch page: never handed out, used as the
  write/gather target for inactive slots and padded block-table entries
  (always masked by seq_lens, so its garbage is never read into a
  softmax with weight > 0);
- alloc is all-or-nothing: a partial grab is rolled back so a failed
  allocation leaves the free list unchanged (the scheduler turns the
  failure into a preemption, not a torn reservation);
- a page with refcount > 0 is never written by anyone but its single
  writer (shared full pages are immutable; partial pages are shared
  only through COW copies) and never scrubbed — quarantined pages
  (``quarantine``) are deregistered immediately but scrubbed only when
  the last holder releases them (refcount 0).
"""

from __future__ import annotations

import hashlib
import math
import struct
from collections import OrderedDict
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.trace import NULL_TRACER
from ..quantization.serving import QuantizedKV
from .errors import ServingError
from .tiering import HostTier

__all__ = ["KVCachePool", "PoolExhaustedError", "PrefixMatch"]

# chain root for the page-content hash (the "parent" of the first page).
# A quantized pool chains from a DIFFERENT root (the mode tag hashed in),
# so an fp-cache hash and an int8-cache hash of the same tokens can never
# alias: the hash names the page *content* (KV bytes + scales), and the
# same tokens produce different content under the two storage formats.
_HASH_ROOT = b"\x00" * 16
_HASH_ROOT_INT8 = hashlib.blake2b(b"paddle_tpu.kv.int8",
                                  digest_size=16).digest()


def _page_copy(arr, src: int, dst: int, stacked: bool = False):
    """Device-copy one page; a QuantizedKV page carries its scale row
    along with the int8 codes (COW without the scales would dequantize
    the copy with garbage). ``stacked`` indexes pages on dim 1 of the
    pipeline-stacked ``[L, pages, ...]`` layout — the copy spans every
    layer, same as the per-layer list-comprehension it replaces."""
    if isinstance(arr, QuantizedKV):
        return QuantizedKV(_page_copy(arr.q, src, dst, stacked),
                           _page_copy(arr.scale, src, dst, stacked))
    if stacked:
        return arr.at[:, dst].set(arr[:, src])
    return arr.at[dst].set(arr[src])


def _page_zero(arr, idx, stacked: bool = False):
    """Zero pages; a QuantizedKV page zeroes codes AND scales — a scrub
    that left a poisoned (NaN) scale row behind would re-poison the next
    tenant on its first dequantized read."""
    if isinstance(arr, QuantizedKV):
        return QuantizedKV(_page_zero(arr.q, idx, stacked),
                           _page_zero(arr.scale, idx, stacked))
    if stacked:
        return arr.at[:, idx].set(0)
    return arr.at[idx].set(0)


def _page_hash(parent: bytes, tokens) -> bytes:
    """Chained page-content key: H(parent_hash, page_tokens). Collision
    resistance matters — a false positive would serve another prompt's
    KV — so this is blake2b-128 over the exact token bytes, not
    Python's 64-bit ``hash``."""
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(struct.pack(f"<{len(tokens)}q", *tokens))
    return h.digest()


class PoolExhaustedError(ServingError):
    """Raised by ``alloc`` when the pool cannot satisfy a request; the
    scheduler catches it and preempts (never propagates to users)."""


@dataclass
class PrefixMatch:
    """Result of ``match_prefix``: the longest cached prefix of a token
    sequence, at page granularity. ``full_pages`` are immutable shared
    pages to map directly; ``partial_page`` (if any) must be reused via
    ``cow_into`` a freshly-allocated page, never written in place.

    With a host tier attached the walk continues past the last
    HBM-resident full page: ``chain`` holds the content-hash keys of
    the continuation full pages, each resolvable in HBM OR the host
    tier at match time (re-resolved HBM-first at restore time — a page
    re-registered since its spill wins over the host copy), and
    ``host_partial_key`` names a host-tier partial tail. ``host_tokens``
    counts the tokens that would have to be RESTORED (host-resolved at
    match time) — the scheduler's restore-budget charge is computed
    from it. ``cached_tokens`` keeps its pre-tier meaning (the
    HBM-contiguous prefix); ``total_cached`` is the full hierarchy
    match the admission actually targets."""
    full_pages: list[int] = field(default_factory=list)
    partial_page: int | None = None
    partial_len: int = 0
    cached_tokens: int = 0
    chain: list[bytes] = field(default_factory=list)
    host_tokens: int = 0
    host_partial_key: bytes | None = None
    host_partial_len: int = 0
    total_cached: int = 0

    @property
    def hit(self) -> bool:
        return self.cached_tokens > 0 or self.total_cached > 0


class KVCachePool:
    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.bfloat16,
                 cache_enabled: bool = True, quantized: bool = False,
                 host_tier=None, sharding=None, tp_degree: int = 1,
                 pp_degree: int = 1):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved scratch page)")
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.quantized = quantized
        self.dtype = jnp.int8 if quantized else dtype
        # tensor parallelism (serving/parallel.py): ``sharding`` is a
        # (payload, scale) NamedSharding pair splitting the kv-head dim
        # over the mp mesh. The arrays stay GLOBAL logical jax.Arrays —
        # every host-side path below (alloc/refcount/hash metadata,
        # .at[].set writes, device_get spill/snapshot capture) is
        # tp-agnostic because sharding is a layout, not a shape change.
        # Pipeline parallelism stacks the per-layer pairs into ONE
        # [num_layers, pages, ...] pair whose leading dim splits on the
        # pp mesh axis — each stage's HBM holds only its own layers'
        # pages (the ~1/pp per-chip KV saving); ``stacked`` flags the
        # layout and every content-touching path below branches on it.
        # The HOST payload format (per layer k then v) is unchanged, so
        # spills and snapshots stay portable across pp degrees.
        self.sharding = sharding
        self.tp_degree = int(tp_degree)
        self.pp_degree = int(pp_degree)
        self.stacked = self.pp_degree > 1
        shape = (num_pages, page_size, num_kv_heads, head_dim)
        if self.stacked:
            shape = (num_layers,) + shape

        def _place(z, scale=False):
            if sharding is None:
                return z
            return jax.device_put(z, sharding[1] if scale else sharding[0])
        # per-layer (pool_k, pool_v); functionally replaced by the compiled
        # programs each step, so the handles here always name the latest.
        # Quantized mode stores int8 codes + one fp32 absmax scale per
        # [page, slot, kv_head] row (see quantization/serving.py).
        if quantized:
            def _zeros():
                return QuantizedKV(
                    _place(jnp.zeros(shape, jnp.int8)),
                    _place(jnp.zeros(shape[:-1], jnp.float32), scale=True))
            self.pools = [(_zeros(), _zeros())
                          for _ in range(1 if self.stacked else num_layers)]
        else:
            self.pools = [(_place(jnp.zeros(shape, dtype)),
                           _place(jnp.zeros(shape, dtype)))
                          for _ in range(1 if self.stacked else num_layers)]
        # fp and int8 caches chain their content hashes from different
        # roots — same tokens, different page content, never aliased
        self._hash_root = _HASH_ROOT_INT8 if quantized else _HASH_ROOT
        # host-RAM spill tier (serving/tiering.py): True -> defaults,
        # an int -> byte budget, or a ready HostTier (shareable across
        # homogeneous pools — identical weights produce identical KV
        # bytes, and the dtype tag below keeps formats from aliasing)
        if host_tier is True:
            host_tier = HostTier()
        elif isinstance(host_tier, int) and not isinstance(host_tier, bool):
            host_tier = HostTier(max_bytes=host_tier)
        self.host_tier: HostTier | None = host_tier
        self._tier_tag = "int8" if quantized else str(jnp.dtype(self.dtype))
        # LIFO free list, page 0 reserved (scratch)
        self._free = list(range(num_pages - 1, 0, -1))
        # pages known to hold all-zero content: everything at
        # construction, re-added by scrub(), dropped at handout or any
        # host-payload write. audit()'s scrubbed-means-zero check reads
        # the device content of (free ∩ scrubbed) pages against this.
        self._scrubbed: set[int] = set(range(1, num_pages))
        self._peak_in_use = 0
        # fault-draw step context for the serving.alloc site, advanced by
        # the engine once per step — without it, probabilistic specs
        # would fall back to the process-global training-step cursor and
        # draw ONE outcome for the engine's whole lifetime
        self.fault_step: int | None = None
        # optional match-path for the serving.alloc site; the fleet
        # router sets it to the replica index so a FaultSpec with
        # ``match=r"^0$"`` pins an alloc storm to one replica
        self.fault_path: str | None = None

        # ---- prefix cache state (all host-side integers) ----
        self.cache_enabled = cache_enabled
        self._ref: dict[int, int] = {}          # page -> refcount (>0 only)
        self._full_index: dict[bytes, int] = {}      # chained hash -> page
        self._partial_index: dict[bytes, int] = {}   # chained hash -> page
        self._page_key: dict[int, tuple[str, bytes]] = {}  # page -> index key
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # refcount-0 cached
        self._scrub_on_zero: set[int] = set()   # quarantined, shared pages
        # injected by the engine when tracing is on; pool events (LRU
        # eviction, COW copies, quarantine) land on the "pool" track
        self.tracer = NULL_TRACER
        self.counters: dict[str, int] = {
            "prefix_lookups": 0, "prefix_hits": 0, "prefix_hit_pages": 0,
            "prefix_partial_hits": 0, "prefix_evictions": 0,
            "prefix_cow_copies": 0, "prefix_pages_registered": 0,
            "rewound_tokens": 0,
        }

    @classmethod
    def from_config(cls, config, num_pages: int, page_size: int,
                    dtype=jnp.bfloat16, cache_enabled: bool = True,
                    quantized: bool = False, host_tier=None,
                    sharding=None, tp_degree: int = 1,
                    pp_degree: int = 1) -> "KVCachePool":
        """Build from a model config carrying num_hidden_layers /
        num_key_value_heads / head_dim (LlamaConfig shape)."""
        return cls(config.num_hidden_layers, num_pages, page_size,
                   config.num_key_value_heads, config.head_dim, dtype,
                   cache_enabled=cache_enabled, quantized=quantized,
                   host_tier=host_tier, sharding=sharding,
                   tp_degree=tp_degree, pp_degree=pp_degree)

    # ---- accounting ----

    @property
    def capacity(self) -> int:
        """Allocatable pages (excludes the scratch page)."""
        return self.num_pages - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_cached(self) -> int:
        """Refcount-0 pages kept resident for prefix reuse (evictable)."""
        return len(self._lru)

    @property
    def num_available(self) -> int:
        """Pages an ``alloc`` can hand out: free + evictable cached."""
        return len(self._free) + len(self._lru)

    @property
    def num_in_use(self) -> int:
        """Pages pinned by live requests (refcount > 0). Cached
        refcount-0 pages are NOT in use — they are reclaimable."""
        return self.capacity - len(self._free) - len(self._lru)

    def utilization(self) -> float:
        return self.num_in_use / self.capacity

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold n_tokens cache positions."""
        return max(1, math.ceil(n_tokens / self.page_size))

    def kv_bytes_per_token(self) -> int:
        """HBM bytes ONE cached token position costs across all layers
        (K+V): the per-token KV traffic unit the int8 bench configs score
        MBU against. Quantized: 1 byte/element of codes plus the fp32
        scale per kv-head row; fp: itemsize bytes/element."""
        kvh, d = self.num_kv_heads, self.head_dim
        if self.quantized:
            per = kvh * d * 1 + kvh * 4   # int8 codes + fp32 scale row
        else:
            per = kvh * d * jnp.dtype(self.dtype).itemsize
        return 2 * self.num_layers * per

    def kv_bytes_per_token_shard(self) -> int:
        """Per-DEVICE bytes one cached token costs under tensor /
        pipeline parallelism: the kv-head dim is split tp ways (each
        shard holds ``kvh/tp`` heads of every page) and the stacked
        layer dim pp ways (each stage holds only its own ``L/pp``
        layers' pages), so the per-chip figure is the full cost over
        ``tp * pp`` (== the full figure at tp=pp=1). The per-chip HBM
        budget a parallel deployment plans against."""
        return (self.kv_bytes_per_token()
                // max(self.tp_degree, 1) // max(self.pp_degree, 1))

    def stats(self) -> dict:
        # host-tier breakdown rides along (schema-stable zeros when the
        # tier is off) so dashboards reading pool stats don't need a
        # second call — and observability.render_prometheus turns every
        # numeric key here into a paddle_serving_pool_* gauge (the tp_*
        # keys below become the paddle_serving_pool_tp_* family)
        tier = (self.host_tier.stats() if self.host_tier is not None
                else HostTier.zero_stats())
        shard_bpt = self.kv_bytes_per_token_shard()
        return {"num_pages": self.num_pages, "page_size": self.page_size,
                "capacity": self.capacity, "in_use": self.num_in_use,
                "pinned": self.num_in_use, "cached": self.num_cached,
                "free": self.num_free, "utilization": self.utilization(),
                "peak_in_use": self._peak_in_use,
                "indexed_pages": len(self._page_key),
                "kv_quant": int(self.quantized),
                "host_tier": int(self.host_tier is not None),
                "tp_degree": self.tp_degree,
                "pp_degree": self.pp_degree,
                "pp_stage_layers":
                    self.num_layers // max(self.pp_degree, 1),
                "tp_shard_kv_bytes_per_token": shard_bpt,
                "tp_shard_in_use_bytes":
                    self.num_in_use * self.page_size * shard_bpt,
                "tp_shard_capacity_bytes":
                    self.capacity * self.page_size * shard_bpt,
                **tier,
                **self.counters}

    # ---- alloc / free ----

    def alloc(self, n: int) -> list[int]:
        """Grab n pages (all-or-nothing); raises PoolExhaustedError.

        The free list is consumed first; when it runs dry, refcount-0
        cached pages are evicted LRU-oldest — deregistered from the
        prefix index and scrubbed back to zero (the masked-garbage-is-
        zero invariant survives reuse) — until the grab fits. Pinned
        pages (refcount > 0) are never touched.

        Fault site ``serving.alloc``: an armed ``raise`` spec here
        surfaces as a PoolExhaustedError — the scheduler's normal
        exhaustion path — so chaos tests can drive deterministic
        pool-exhaustion storms (preemption cascades) without actually
        shrinking the pool."""
        from ..distributed import fault as _fault
        try:
            _fault.trip("serving.alloc", step=self.fault_step,
                        path=self.fault_path,
                        need=n, free=self.num_available)
        except _fault.FaultInjected as e:
            raise PoolExhaustedError(
                f"injected exhaustion (serving.alloc): {e}") from e
        if n > self.num_available:
            raise PoolExhaustedError(
                f"need {n} pages, {len(self._free)} free + "
                f"{len(self._lru)} cached (capacity {self.capacity})")
        evicted: list[int] = []
        while len(self._free) < n and self._lru:
            page, _ = self._lru.popitem(last=False)  # oldest first
            self._spill(page)   # demote to the host tier (if attached)
                                # BEFORE the index key is forgotten
            self._deregister(page)
            evicted.append(page)
            self._free.append(page)
        if evicted:
            self.scrub(evicted)
            self.counters["prefix_evictions"] += len(evicted)
            self.tracer.instant("prefix_evict", track="pool",
                                pages=len(evicted))
            self.tracer.bump("prefix_evictions", len(evicted),
                             track="pool")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
            self._scrubbed.discard(p)
        self._peak_in_use = max(self._peak_in_use, self.num_in_use)
        return pages

    def free(self, pages: list[int]) -> None:
        """Unconditionally return pages to the free list (no refcount /
        cache semantics — the low-level inverse of ``alloc``). The
        refcounted paths go through ``release``."""
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"page {p} is not an allocatable page")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        for p in pages:
            self._ref.pop(p, None)
            self._lru.pop(p, None)
            self._scrub_on_zero.discard(p)
            self._deregister(p)
        self._free.extend(pages)

    # ---- reference counting ----

    def acquire(self, pages: list[int]) -> None:
        """Take a reference on each page (a cache hit mapping shared
        pages into a block table). A refcount-0 cached page is pinned —
        pulled off the eviction LRU — by its first new holder."""
        for p in pages:
            r = self._ref.get(p, 0)
            if r == 0:
                self._lru.pop(p, None)
            self._ref[p] = r + 1
        self._peak_in_use = max(self._peak_in_use, self.num_in_use)

    def release(self, pages: list[int]) -> None:
        """Drop one reference per page. At refcount 0 a page either
        stays resident as a cached page (registered in the prefix index
        and cache enabled), is scrubbed-then-freed (quarantined), or
        returns to the free list."""
        scrub: list[int] = []
        for p in pages:
            r = self._ref.get(p, 0) - 1
            if r > 0:
                self._ref[p] = r
                continue
            self._ref.pop(p, None)
            if p in self._scrub_on_zero:
                # quarantined while shared: only now, with no holder
                # left, is it safe to zero the poisoned content
                self._scrub_on_zero.discard(p)
                self._deregister(p)
                scrub.append(p)
                self._free.append(p)
            elif self.cache_enabled and p in self._page_key:
                self._lru[p] = None
                self._lru.move_to_end(p)
            else:
                self._deregister(p)
                self._free.append(p)
        if scrub:
            self.scrub(scrub)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def quarantine(self, pages: list[int]) -> None:
        """Poison containment for a request whose pages may hold
        non-finite values: deregister every page from the prefix index
        immediately (no future request may match it) and mark it
        scrub-on-zero. Pages still shared with live requests are NOT
        scrubbed here — zeroing under a reader would corrupt its
        stream; the scrub happens in ``release`` when the last
        reference drops. A quarantined page's host-tier entry is purged
        too — poisoned content must not survive in ANY tier — and the
        scrub-on-zero mark keeps the page from ever spilling later."""
        todo = []
        for p in set(pages):
            kk = self._page_key.get(p)
            if kk is not None and self.host_tier is not None:
                self.host_tier.discard(self._tier_tag, *kk)
            self._deregister(p)
            if self._ref.get(p, 0) > 0:
                self._scrub_on_zero.add(p)
            elif p in self._lru:        # cached, no holders: scrub now
                self._lru.pop(p)
                todo.append(p)
                self._free.append(p)
        if todo:
            self.scrub(todo)
        self.tracer.instant("quarantine", track="pool",
                            pages=len(set(pages)))

    # ---- the prefix index ----

    def _namespaced_root(self, namespace: bytes = b"") -> bytes:
        """Chain root for a (possibly namespaced) prefix walk. A LoRA
        request's KV depends on its adapter — the same system prompt
        produces DIFFERENT page content under adapter X and adapter Y —
        so each adapter's chain starts from a root derived from the
        adapter's content digest, and a cross-adapter lookup can never
        alias (same mechanism as the fp/int8 root split above)."""
        if not namespace:
            return self._hash_root
        return hashlib.blake2b(self._hash_root + namespace,
                               digest_size=16).digest()

    def match_prefix(self, tokens, max_tokens: int | None = None,
                     count: bool = False,
                     namespace: bytes = b"") -> PrefixMatch:
        """Longest cached prefix of ``tokens`` at page granularity:
        full pages walked by the chained content hash, then the longest
        indexed partial continuation of the next page. Pure lookup —
        takes no references (callers ``acquire`` what they keep). Pass
        ``count=True`` to tally the hit counters (one tally per
        admission, not per probe). ``namespace`` scopes the walk to one
        adapter's chain (see ``_namespaced_root``)."""
        limit = len(tokens) if max_tokens is None else min(max_tokens,
                                                           len(tokens))
        m = PrefixMatch()
        if not self.cache_enabled or limit <= 0:
            return m
        ps = self.page_size
        tier = self.host_tier
        parent = self._namespaced_root(namespace)
        pos = 0
        while pos + ps <= limit:
            key = _page_hash(parent, tokens[pos:pos + ps])
            page = self._full_index.get(key)
            if page is None:
                break
            m.full_pages.append(page)
            parent = key
            pos += ps
        # host-tier continuation: keep walking the SAME content-hash
        # chain past the HBM break, accepting a page wherever it is
        # resolvable — HBM first (a mid-chain page can be HBM-resident
        # while an earlier one was evicted: eviction drops only its own
        # key), then the host tier. The keys are recorded, not pages:
        # restore_chain re-resolves each one at commit time.
        m.cached_tokens = pos
        if tier is not None:
            while pos + ps <= limit:
                key = _page_hash(parent, tokens[pos:pos + ps])
                if key in self._full_index:
                    pass
                elif tier.has(self._tier_tag, "full", key):
                    m.host_tokens += ps
                else:
                    break
                m.chain.append(key)
                parent = key
                pos += ps
        for q in range(min(limit - pos, ps - 1), 0, -1):
            key = _page_hash(parent, tokens[pos:pos + q])
            page = self._partial_index.get(key)
            if page is not None:
                m.partial_page, m.partial_len = page, q
                break
            if tier is not None and tier.has(self._tier_tag, "partial",
                                             key):
                m.host_partial_key, m.host_partial_len = key, q
                m.host_tokens += q
                break
        if not m.chain:
            m.cached_tokens += m.partial_len
        m.total_cached = pos + m.partial_len + m.host_partial_len
        if count:
            self.count_match(m)
        return m

    def count_match(self, m: PrefixMatch) -> None:
        self.counters["prefix_lookups"] += 1
        if m.hit:
            has_partial = (m.partial_page is not None
                           or m.host_partial_key is not None)
            self.counters["prefix_hits"] += 1
            self.counters["prefix_hit_pages"] += (
                len(m.full_pages) + len(m.chain) + (1 if has_partial else 0))
            if has_partial:
                self.counters["prefix_partial_hits"] += 1

    def register_prefix(self, tokens, pages: list[int],
                        include_partial: bool = True,
                        namespace: bytes = b"") -> int:
        """Index a request's materialized prefix: page i of ``pages``
        holds ``tokens[i*ps:(i+1)*ps]``. Full pages are registered under
        the chained hash; the trailing partial page (content frozen —
        callers register it only once no further writes can land, i.e.
        at release) under the partial index. The chunked engine calls
        this only when the FINAL prefill chunk lands (never for a
        prompt still streaming in chunks — a mid-prompt preemption must
        leave nothing indexed); the unchunked arm registers inside the
        admission loop right after the whole-suffix prefill. First
        writer wins: an existing index entry for the same content keeps
        its page. Pages must be held by the caller (refcount > 0);
        returns how many pages were newly registered."""
        if not self.cache_enabled:
            return 0
        ps = self.page_size
        n_full = min(len(tokens) // ps, len(pages))
        parent = self._namespaced_root(namespace)
        registered = 0
        for i in range(n_full):
            key = _page_hash(parent, tokens[i * ps:(i + 1) * ps])
            page = pages[i]
            if (key not in self._full_index and page not in self._page_key
                    and self._ref.get(page, 0) > 0
                    and page not in self._scrub_on_zero):
                self._full_index[key] = page
                self._page_key[page] = ("full", key)
                registered += 1
            parent = key  # the content chain continues either way
        q = len(tokens) - n_full * ps
        if include_partial and 0 < q < ps and n_full < len(pages):
            key = _page_hash(parent, tokens[n_full * ps:])
            page = pages[n_full]
            if (key not in self._partial_index and page not in self._page_key
                    and self._ref.get(page, 0) > 0
                    and page not in self._scrub_on_zero):
                self._partial_index[key] = page
                self._page_key[page] = ("partial", key)
                registered += 1
        self.counters["prefix_pages_registered"] += registered
        return registered

    def _deregister(self, page: int) -> None:
        kind_key = self._page_key.pop(page, None)
        if kind_key is None:
            return
        kind, key = kind_key
        index = self._full_index if kind == "full" else self._partial_index
        if index.get(key) == page:
            del index[key]

    # ---- host tier: spill on evict, restore on hit ----
    # (serving/tiering.py; SERVING.md "KV tiering & traffic harness").
    # All transfers here are host-side device_get/device_put around
    # functional .at[] updates — never inside a compiled program, so the
    # engine's decode/mixed program counts are untouched.

    def _spill(self, page: int) -> None:
        """Demote an LRU-evicted page's content to the host tier —
        called from ``alloc`` BEFORE deregistration, while the page's
        index key is still known. Quarantined content never spills:
        quarantine pulls its pages off the LRU and purges their index
        keys immediately, and the scrub-on-zero guard here covers any
        remaining window. Fault site ``serving.spill``: ``raise`` drops
        the spill (the page is simply lost, exactly as without a tier);
        ``poison`` corrupts the stored payload after the fact, so the
        restore-side digest re-verify MUST catch it."""
        tier = self.host_tier
        if tier is None:
            return
        kk = self._page_key.get(page)
        if kk is None or page in self._scrub_on_zero:
            return
        kind, key = kk
        if not tier.put(self._tier_tag, kind, key,
                        self._page_payload(page)):
            return
        from ..distributed import fault as _fault
        try:
            _fault.trip("serving.spill", step=self.fault_step,
                        path=key.hex(), page=page,
                        poison=lambda: tier.corrupt(self._tier_tag,
                                                    kind, key))
        except _fault.FaultInjected:
            tier.discard(self._tier_tag, kind, key)
            tier.counters["spill_dropped"] += 1
            return
        self.tracer.instant("spill", track="pool", page=page, kind=kind)
        self.tracer.bump("spills", 1, track="pool")

    def _page_parts(self, page: int) -> list:
        """One page's device slices in the host payload order: per layer
        k then v (quantized: codes then scales). The stacked pp layout
        iterates its layer dim so the payload format is IDENTICAL to the
        per-layer list — pp-portable by construction."""
        parts = []
        if self.stacked:
            (pk, pv), = self.pools
            for li in range(self.num_layers):
                for arr in (pk, pv):
                    if isinstance(arr, QuantizedKV):
                        parts.append(arr.q[li, page])
                        parts.append(arr.scale[li, page])
                    else:
                        parts.append(arr[li, page])
            return parts
        for pk, pv in self.pools:
            for arr in (pk, pv):
                if isinstance(arr, QuantizedKV):
                    parts.append(arr.q[page])
                    parts.append(arr.scale[page])
                else:
                    parts.append(arr[page])
        return parts

    def _page_payload(self, page: int) -> list:
        """One page's bytes as host numpy arrays, per layer in pool
        order (k then v; a quantized pool interleaves codes and scales
        — spilling codes without scales would dequantize the restore
        with garbage). One batched device_get for the whole page."""
        parts = self._page_parts(page)
        if self.tp_degree > 1:
            # the device_get below collects every shard's kvh/tp heads
            # into the full logical page — the HostTier payload format
            # stays tp-portable (a tp=2 spill restores into tp=1)
            self.tracer.instant("shard_gather", track="pool", page=page,
                                tp=self.tp_degree, kind="spill")
        return [np.asarray(x) for x in jax.device_get(parts)]

    def export_pages(self, pages: list[int]) -> list[list[np.ndarray]]:
        """Export many pages' payloads with ONE batched device_get:
        returns one ``_page_payload``-format array list per page, in
        input order. This is the snapshot capture primitive
        (serving/snapshot.py) — a host-side transfer outside every
        compiled program, so ``step_program_counts()`` is untouched."""
        if not pages:
            return []
        parts = []
        for page in pages:
            parts.extend(self._page_parts(page))
        if self.tp_degree > 1:
            # shard-gather: snapshot payloads hold full logical pages,
            # so a tp=2 snapshot restores into a tp=1 engine (and back)
            self.tracer.instant("shard_gather", track="pool",
                                pages=len(pages), tp=self.tp_degree,
                                kind="snapshot")
        flat = [np.asarray(x) for x in jax.device_get(parts)]
        k = len(flat) // len(pages)
        return [flat[i * k:(i + 1) * k] for i in range(len(pages))]

    def _write_host_page(self, page: int, arrays) -> None:
        """device_put a host payload back into HBM page ``page`` (the
        inverse of ``_page_payload``, bit-exact: get/put round-trips
        bf16, fp32 and int8 bytes unchanged)."""
        self._scrubbed.discard(page)
        it = iter(arrays)
        if self.stacked:
            (pk, pv), = self.pools
            pair = [pk, pv]
            for li in range(self.num_layers):
                for i in range(2):
                    arr = pair[i]
                    if isinstance(arr, QuantizedKV):
                        q = jnp.asarray(next(it), arr.q.dtype)
                        s = jnp.asarray(next(it), arr.scale.dtype)
                        pair[i] = QuantizedKV(
                            arr.q.at[li, page].set(q),
                            arr.scale.at[li, page].set(s))
                    else:
                        pair[i] = arr.at[li, page].set(
                            jnp.asarray(next(it), arr.dtype))
            self.pools = [tuple(pair)]
            return
        new_pools = []
        for pk, pv in self.pools:
            pair = []
            for arr in (pk, pv):
                if isinstance(arr, QuantizedKV):
                    q = jnp.asarray(next(it), arr.q.dtype)
                    s = jnp.asarray(next(it), arr.scale.dtype)
                    pair.append(QuantizedKV(arr.q.at[page].set(q),
                                            arr.scale.at[page].set(s)))
                else:
                    pair.append(arr.at[page].set(
                        jnp.asarray(next(it), arr.dtype)))
            new_pools.append(tuple(pair))
        self.pools = new_pools

    def restore_charge(self, m: PrefixMatch | None) -> int:
        """Prefill-budget tokens the match's host-resolved tokens would
        cost to restore (the admission-time optimistic charge)."""
        if m is None or self.host_tier is None:
            return 0
        return self.host_tier.restore_charge(m.host_tokens)

    def restore_charge_tokens(self, restored_tokens: int) -> int:
        """Budget charge for tokens ACTUALLY restored (the post-commit
        number the engine mirrors into its own budget bookkeeping)."""
        if self.host_tier is None:
            return 0
        return self.host_tier.restore_charge(restored_tokens)

    def restore_chain(self, m: PrefixMatch) -> tuple[list[int], int]:
        """Map the continuation ``m.chain`` into HBM in chain order.
        Each key is re-resolved HBM-first — a page (re-)registered since
        the match, including by an earlier restore in this very loop,
        wins and is simply acquired (the restore-racing-re-registration
        rule) — else its payload is fetched from the host tier, written
        into a freshly-allocated page and registered under the key.
        Stops at the first failure (host miss, corrupt payload, injected
        ``serving.restore`` fault, pool exhaustion): the chain beyond it
        falls back to recompute. Returns ``(pages, restored_tokens)``;
        every returned page carries one reference for the caller."""
        pages: list[int] = []
        restored_tok = 0
        tier = self.host_tier
        from ..distributed import fault as _fault
        for key in m.chain:
            page = self._full_index.get(key)
            if page is not None:
                self.acquire([page])
                pages.append(page)
                continue
            if tier is None:
                break
            try:
                _fault.trip("serving.restore", step=self.fault_step,
                            path=key.hex(),
                            poison=lambda k=key: tier.corrupt(
                                self._tier_tag, "full", k))
            except _fault.FaultInjected:
                tier.counters["restore_failed"] += 1
                break
            arrays = tier.fetch(self._tier_tag, "full", key)
            if arrays is None:
                break
            try:
                page = self.alloc(1)[0]
            except PoolExhaustedError:
                break
            self._write_host_page(page, arrays)
            # first-writer-wins still holds: the key was absent from the
            # index at the top of this iteration and nothing since could
            # have inserted it (our own alloc only EVICTS entries)
            self._full_index[key] = page
            self._page_key[page] = ("full", key)
            nbytes = sum(a.nbytes for a in arrays)
            tier.on_restored(nbytes)
            restored_tok += self.page_size
            self.tracer.instant("restore", track="pool", page=page,
                                bytes=nbytes)
            self.tracer.bump("restores", 1, track="pool")
            pages.append(page)
        return pages, restored_tok

    def fetch_host_partial(self, m: PrefixMatch):
        """Fetch the match's host-tier partial payload (or None on
        miss/corruption/injected fault). Separate from
        ``restore_partial_into`` because the caller allocates the
        destination page between the two."""
        tier = self.host_tier
        if tier is None or m.host_partial_key is None:
            return None
        from ..distributed import fault as _fault
        key = m.host_partial_key
        try:
            _fault.trip("serving.restore", step=self.fault_step,
                        path=key.hex(),
                        poison=lambda: tier.corrupt(self._tier_tag,
                                                    "partial", key))
        except _fault.FaultInjected:
            tier.counters["restore_failed"] += 1
            return None
        return tier.fetch(self._tier_tag, "partial", key)

    def restore_partial_into(self, dst: int, arrays) -> None:
        """Restore a host partial payload straight into the hitter's
        first fresh suffix page: the copy-at-map COW rule with the copy
        sourced from host RAM. ``dst`` is private to the hitter and is
        NOT registered here — like a COW copy, it re-enters the index
        at release under its own (longer) key. Positions beyond the
        partial length were zero when the page spilled, so the
        masked-garbage-is-zero invariant rides through the round
        trip."""
        self._write_host_page(dst, arrays)
        nbytes = sum(np.asarray(a).nbytes for a in arrays)
        if self.host_tier is not None:
            self.host_tier.on_restored(nbytes)
        self.tracer.instant("restore", track="pool", page=dst,
                            bytes=nbytes, partial=True)
        self.tracer.bump("restores", 1, track="pool")

    def inject_prefix(self, tokens, payloads,
                      namespace: bytes = b"") -> int:
        """Write externally-held page payloads (a request snapshot —
        serving/snapshot.py) into the pool and register them under the
        chained content hash as refcount-0 CACHED pages, exactly as if
        a request with this prefix had just released them. Page i of
        ``payloads`` holds ``tokens[i*ps:(i+1)*ps]`` in
        ``_page_payload`` format; a trailing partial page (0 < q < ps
        tokens, zeros beyond) lands in the partial index. The ordinary
        admission path (``match_prefix`` + ``acquire`` + COW) then maps
        them — restore needs no new engine machinery, and an injected
        page LRU-evicted before its request re-admits degrades to a
        plain recompute, never a wrong token. First writer wins:
        content already indexed keeps its resident page (those tokens
        still count as injected — they are matchable). Stops early on
        pool exhaustion. Returns the matchable token count."""
        if not self.cache_enabled:
            return 0
        ps = self.page_size
        n_full = len(tokens) // ps
        parent = self._namespaced_root(namespace)
        injected = 0
        for i in range(min(n_full, len(payloads))):
            key = _page_hash(parent, tokens[i * ps:(i + 1) * ps])
            if key not in self._full_index:
                try:
                    page = self.alloc(1)[0]
                except PoolExhaustedError:
                    return injected
                self._write_host_page(page, payloads[i])
                self._full_index[key] = page
                self._page_key[page] = ("full", key)
                self.counters["prefix_pages_registered"] += 1
                self.release([page])   # registered + refcount 0 -> LRU
            parent = key
            injected += ps
        q = len(tokens) - n_full * ps
        if 0 < q < ps and n_full < len(payloads):
            key = _page_hash(parent, tokens[n_full * ps:])
            if key not in self._partial_index:
                try:
                    page = self.alloc(1)[0]
                except PoolExhaustedError:
                    return injected
                self._write_host_page(page, payloads[n_full])
                self._partial_index[key] = page
                self._page_key[page] = ("partial", key)
                self.counters["prefix_pages_registered"] += 1
                self.release([page])
            injected += q
        return injected

    # ---- device-side page ops ----

    def cow_into(self, src: int, dst: int) -> None:
        """Copy-on-write materialization: device-copy page ``src`` into
        the freshly-allocated page ``dst``. The cached source is never
        written in place — the hitter extends its own copy."""
        self.pools = [(_page_copy(pk, src, dst, self.stacked),
                       _page_copy(pv, src, dst, self.stacked))
                      for pk, pv in self.pools]
        self.counters["prefix_cow_copies"] += 1
        self.tracer.instant("cow_copy", track="pool", src=src, dst=dst)

    def scrub(self, pages: list[int]) -> None:
        """Zero pages (eviction / quarantine): restores the
        masked-garbage-is-zero invariant before reuse."""
        if not pages:
            return
        idx = jnp.asarray(sorted(set(pages)), jnp.int32)
        self.pools = [(_page_zero(pk, idx, self.stacked),
                       _page_zero(pv, idx, self.stacked))
                      for pk, pv in self.pools]
        self._scrubbed.update(int(p) for p in pages)

    def rewind(self, pages: list[int], start: int, stop: int) -> None:
        """Zero cache POSITIONS ``[start, stop)`` of a request's block
        table (token-granular, unlike page-granular ``scrub``): the
        speculative rollback primitive. The verify step writes draft KV
        optimistically at positions ``context_len..context_len+n_draft``;
        the compiled step zeroes rejected rows in-program, and the
        engine calls this for the host-side cases (accepted-but-unused
        tail when eos/length lands inside the accept window) so a
        partial-page tail never leaves garbage beyond the request's
        ``context_len`` — masked-garbage-is-zero, preserved at token
        granularity. Pages written speculatively are always private to
        the request (shared full pages are immutable; COW copies partial
        heads), so zeroing here can never damage another request's KV."""
        if stop <= start:
            return
        ps = self.page_size
        pg = jnp.asarray([pages[p // ps] for p in range(start, stop)],
                         jnp.int32)
        off = jnp.asarray([p % ps for p in range(start, stop)], jnp.int32)
        self.pools = [(self._pos_zero(pk, pg, off, self.stacked),
                       self._pos_zero(pv, pg, off, self.stacked))
                      for pk, pv in self.pools]
        self.counters["rewound_tokens"] += stop - start

    @staticmethod
    def _pos_zero(arr, pages, offs, stacked: bool = False):
        """Zero individual (page, offset) rows; QuantizedKV zeroes codes
        AND scales (same reasoning as ``_page_zero``). ``stacked``
        addresses the pipeline layout's ``[L, pages, ...]`` arrays —
        the zero spans every layer, like the per-layer loop."""
        if isinstance(arr, QuantizedKV):
            return QuantizedKV(
                KVCachePool._pos_zero(arr.q, pages, offs, stacked),
                KVCachePool._pos_zero(arr.scale, pages, offs, stacked))
        if stacked:
            return arr.at[:, pages, offs].set(0)
        return arr.at[pages, offs].set(0)

    # ---- invariant audit ----

    def audit(self, block_tables=None, check_device: bool = True) -> dict:
        """Invariant checker for the pool's host-side accounting —
        called from serving test teardowns and the faults-marked chaos
        suites, so every chaos scenario proves it left the pool
        consistent, not just that the streams came out right. Raises
        AssertionError listing every violated invariant:

        - free-list hygiene: no duplicates, never the scratch page,
          disjoint from held (refcount > 0) and cached (LRU) pages;
        - conservation: free ∪ cached ∪ held covers every allocatable
          page exactly once;
        - refcounts: strictly positive, and — given ``block_tables``
          (one page list per live request) — equal to the number of
          holders per page, with no held page missing a holder;
        - index agreement: ``_page_key`` and the full/partial indexes
          are exact inverses, an indexed page is never free, an LRU
          page is always registered, and a quarantined (scrub-on-zero)
          page is held and never indexed;
        - scrubbed-means-zero (``check_device``): every free page the
          pool believes it scrubbed reads back all-zero on device —
          codes AND scales in int8 mode (a NaN can't hide: NaN != 0).

        Returns a small accounting dict when everything holds."""
        problems: list[str] = []
        free_list = self._free
        free = set(free_list)
        cached = set(self._lru)
        held = set(self._ref)
        all_pages = set(range(1, self.num_pages))
        if len(free) != len(free_list):
            problems.append("duplicate pages on the free list")
        if 0 in free or 0 in cached or 0 in held:
            problems.append("scratch page 0 entered the accounting")
        for a, b, name in ((free, cached, "free∩cached"),
                           (free, held, "free∩held"),
                           (cached, held, "cached∩held")):
            both = a & b
            if both:
                problems.append(f"{name} not disjoint: {sorted(both)}")
        union = free | cached | held
        if union != all_pages:
            missing = sorted(all_pages - union)
            extra = sorted(union - all_pages)
            problems.append(f"page conservation broken: leaked={missing} "
                            f"phantom={extra}")
        for p, r in self._ref.items():
            if r <= 0:
                problems.append(f"page {p} held with refcount {r} <= 0")
        if block_tables is not None:
            holders: dict[int, int] = {}
            for table in block_tables:
                for p in table:
                    holders[p] = holders.get(p, 0) + 1
            for p, r in self._ref.items():
                if holders.get(p, 0) != r:
                    problems.append(
                        f"page {p} refcount {r} != {holders.get(p, 0)} "
                        f"block-table holders")
            for p in holders:
                if p not in self._ref:
                    problems.append(
                        f"page {p} appears in a block table but holds "
                        f"no reference")
        for page, (kind, key) in self._page_key.items():
            index = (self._full_index if kind == "full"
                     else self._partial_index)
            if index.get(key) != page:
                problems.append(
                    f"page {page} claims {kind} key {key.hex()[:8]} but "
                    f"the index maps it to {index.get(key)}")
            if page in free:
                problems.append(f"registered page {page} is on the "
                                f"free list")
        for kind, index in (("full", self._full_index),
                            ("partial", self._partial_index)):
            for key, page in index.items():
                if self._page_key.get(page) != (kind, key):
                    problems.append(
                        f"{kind} index entry {key.hex()[:8]} -> {page} "
                        f"has no matching _page_key back-pointer")
        for p in cached:
            if p not in self._page_key:
                problems.append(f"cached (LRU) page {p} is not "
                                f"registered in any index")
        for p in self._scrub_on_zero:
            if p not in held:
                problems.append(f"scrub-on-zero page {p} has no holder "
                                f"(should have been scrubbed+freed)")
            if p in self._page_key:
                problems.append(f"quarantined page {p} is still in the "
                                f"prefix index")
        if check_device:
            zeroed = sorted(free & self._scrubbed)
            if zeroed:
                idx = jnp.asarray(zeroed, jnp.int32)

                def _sel(arr):
                    # stacked pp layout: pages live on dim 1, and one
                    # slice covers every layer at once
                    return arr[:, idx] if self.stacked else arr[idx]
                for li, (pk, pv) in enumerate(self.pools):
                    for name, arr in (("k", pk), ("v", pv)):
                        if isinstance(arr, QuantizedKV):
                            ok = (bool(jnp.all(_sel(arr.q) == 0))
                                  and bool(jnp.all(_sel(arr.scale) == 0)))
                        else:
                            ok = bool(jnp.all(_sel(arr) == 0))
                        if not ok:
                            problems.append(
                                f"scrubbed free page holds nonzero "
                                f"{name} content in layer {li}")
                    if problems and problems[-1].startswith("scrubbed"):
                        break   # one layer's evidence is enough
        if problems:
            raise AssertionError(
                "KV pool audit failed:\n- " + "\n- ".join(problems))
        return {"pages": self.num_pages - 1, "free": len(free),
                "cached": len(cached), "held": len(held)}
