"""Paged KV-cache pool for the continuous-batching serving engine.

One fixed ``[num_pages, page_size, n_kv_heads, head_dim]`` array pair per
layer (the PagedAttention pool, SOSP '23); sequences own pages through
per-request int32 block tables instead of contiguous ``[B, max_len]``
buffers, so cache memory fragments at page granularity instead of
request granularity and a request's reservation grows one page at a
time as it decodes.

Invariants (relied on by the engine's no-retrace contract, SERVING.md):
- the device arrays are allocated ONCE at pool construction and only
  ever updated functionally inside the compiled prefill/decode programs
  — alloc/free move host-side integers, never device memory;
- page 0 is reserved as the scratch page: never handed out, used as the
  write/gather target for inactive slots and padded block-table entries
  (always masked by seq_lens, so its garbage is never read into a
  softmax with weight > 0);
- alloc is all-or-nothing: a partial grab is rolled back so a failed
  allocation leaves the free list unchanged (the scheduler turns the
  failure into a preemption, not a torn reservation).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from .errors import ServingError

__all__ = ["KVCachePool", "PoolExhaustedError"]


class PoolExhaustedError(ServingError):
    """Raised by ``alloc`` when the pool cannot satisfy a request; the
    scheduler catches it and preempts (never propagates to users)."""


class KVCachePool:
    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.bfloat16):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved scratch page)")
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        shape = (num_pages, page_size, num_kv_heads, head_dim)
        # per-layer (pool_k, pool_v); functionally replaced by the compiled
        # programs each step, so the handles here always name the latest
        self.pools = [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                      for _ in range(num_layers)]
        # LIFO free list, page 0 reserved (scratch)
        self._free = list(range(num_pages - 1, 0, -1))
        self._peak_in_use = 0
        # fault-draw step context for the serving.alloc site, advanced by
        # the engine once per step — without it, probabilistic specs
        # would fall back to the process-global training-step cursor and
        # draw ONE outcome for the engine's whole lifetime
        self.fault_step: int | None = None

    @classmethod
    def from_config(cls, config, num_pages: int, page_size: int,
                    dtype=jnp.bfloat16) -> "KVCachePool":
        """Build from a model config carrying num_hidden_layers /
        num_key_value_heads / head_dim (LlamaConfig shape)."""
        return cls(config.num_hidden_layers, num_pages, page_size,
                   config.num_key_value_heads, config.head_dim, dtype)

    # ---- accounting ----

    @property
    def capacity(self) -> int:
        """Allocatable pages (excludes the scratch page)."""
        return self.num_pages - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_in_use(self) -> int:
        return self.capacity - self.num_free

    def utilization(self) -> float:
        return self.num_in_use / self.capacity

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold n_tokens cache positions."""
        return max(1, math.ceil(n_tokens / self.page_size))

    def stats(self) -> dict:
        return {"num_pages": self.num_pages, "page_size": self.page_size,
                "capacity": self.capacity, "in_use": self.num_in_use,
                "free": self.num_free, "utilization": self.utilization(),
                "peak_in_use": self._peak_in_use}

    # ---- alloc / free ----

    def alloc(self, n: int) -> list[int]:
        """Grab n pages (all-or-nothing); raises PoolExhaustedError.

        Fault site ``serving.alloc``: an armed ``raise`` spec here
        surfaces as a PoolExhaustedError — the scheduler's normal
        exhaustion path — so chaos tests can drive deterministic
        pool-exhaustion storms (preemption cascades) without actually
        shrinking the pool."""
        from ..distributed import fault as _fault
        try:
            _fault.trip("serving.alloc", step=self.fault_step,
                        need=n, free=len(self._free))
        except _fault.FaultInjected as e:
            raise PoolExhaustedError(
                f"injected exhaustion (serving.alloc): {e}") from e
        if n > len(self._free):
            raise PoolExhaustedError(
                f"need {n} pages, {len(self._free)} free "
                f"(capacity {self.capacity})")
        pages = [self._free.pop() for _ in range(n)]
        self._peak_in_use = max(self._peak_in_use, self.num_in_use)
        return pages

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p == 0 or p >= self.num_pages:
                raise ValueError(f"page {p} is not an allocatable page")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(pages)
