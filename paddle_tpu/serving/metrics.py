"""Serving metrics: per-request latency percentiles and engine gauges.

Tracks the three latencies the serving literature reports —
- TTFT  (time to first token): arrival -> first token emitted;
- TPOT  (time per output token): (last_token_t - first_token_t) / (n-1);
- ITL   (inter-token latency): each consecutive token gap —
plus queue-depth and KV-pool-utilization gauges sampled once per engine
step, queue-wait percentiles (arrival -> first admission), and the
failure-outcome counters of the robustness layer (rejects, timeouts,
quarantines, preemption-limit kills, drain evictions — see the
"Serving failure modes" table in SERVING.md). The clock is injectable
so tests (and ``bench.py --dry``) can feed a deterministic virtual
time; deadline enforcement in the engine runs on this same clock, and a
``Tracer`` (paddle_tpu.observability) constructed on the same clock
puts spans and percentiles in one timebase.

``goodput_at_slo`` is the SLO view (ROADMAP item 5): requests/s that
finished normally AND met the TTFT / per-request-ITL-p99 SLOs — the
metric that ranks schedulers, cache tiers and admission policies
against each other, exported via ``summary()`` (``set_slo`` arms the
thresholds) and rendered by ``observability.render_prometheus``.
"""

from __future__ import annotations

import time

__all__ = ["ServingMetrics", "FleetMetrics", "percentile"]


def percentile(values, p: float) -> float:
    """Linearly-interpolated percentile (p in [0, 100]), numpy's default
    ``linear`` method: the rank ``p/100 * (n-1)`` is interpolated
    between its two neighbouring order statistics. 0.0 on empty input."""
    if not values:
        return 0.0
    xs = sorted(values)
    if len(xs) == 1:
        return float(xs[0])
    rank = (p / 100.0) * (len(xs) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(xs) - 1)
    frac = rank - lo
    return float(xs[lo] * (1.0 - frac) + xs[hi] * frac)


class FleetMetrics:
    """Counter bag for the fleet router (serving.fleet.FleetRouter) —
    the numbers SERVING.md "Engine fleet & failover" defines and
    ``observability.render_fleet_prometheus`` exports as
    ``paddle_serving_fleet_*_total``:

    - ``dispatched``        placements onto a replica (incl. replays)
    - ``failovers``         in-flight requests re-queued off a dead replica
    - ``replayed_requests`` re-dispatches that replay a prior stream
    - ``replayed_tokens``   replayed positions verified + suppressed
      (each one is a bitwise determinism check that passed)
    - ``shed``              FleetOverloadedError rejects + terminal sheds
    - ``ejections``         replicas marked DEAD
    - ``breaker_opens``     circuit-breaker CLOSED/HALF_OPEN -> OPEN edges
    - ``probes``            OPEN -> HALF_OPEN probe windows

    Bounded-replay failover (serving/snapshot.py; RESILIENCE.md
    "Serving recovery playbook") adds:

    - ``snapshot_restores``        failover placements seeded from a
      verified snapshot (bounded replay) instead of token 0
    - ``snapshot_fallbacks``       failover placements that wanted a
      snapshot but fell back to full replay (missing/corrupt/unusable)
    - ``recovery_restored_tokens`` tokens skipped by snapshot seeding
    - ``recovery_replayed_tokens`` delta tokens each failover still has
      to re-produce (emitted - seeded; the full-replay arm pays the
      whole emitted count here) — THE bounded-vs-full A/B number
    - ``recovery_ttfrt_p50_s`` / ``_p99_s`` (summary only): ejection ->
      first FRESH post-recovery token, via :meth:`observe_recovery`

    Partition-tolerant transport (serving/transport.py; SERVING.md
    "Fleet transport & membership") adds:

    - ``duplicates_suppressed``  result batches the router's per-replica
      seq dedup collapsed (at-least-once delivery made exactly-once)
    - ``stale_epoch_discarded``  messages from a zombie epoch (a replica
      back from a partition after ejection) counted and dropped — each
      one is the fence doing its job
    - ``lease_expirations``      replicas ejected because their
      heartbeat lease lapsed (no ack within ``lease_steps``)

    Disaggregated prefill/decode serving (``placement="disagg"``;
    SERVING.md "Disaggregated serving") adds the handoff ledger:

    - ``handoff_prefills``   requests whose prefill finished on a
      prefill-role replica (the KV now owes a handoff)
    - ``handoff_offers``     KV_OFFER messages the router received
    - ``handoff_bytes``      payload bytes carried by those offers
    - ``handoff_pulls``      KV_PULL placements that landed on a
      decode-role replica (includes re-pulls after a decode death)
    - ``handoff_commits``    KV_COMMIT releases sent back to the
      prefill replica (frees its held copy)
    - ``handoff_corrupt``    offered payloads the digest gate rejected
      (stripped on the wire, or refused at inject time)
    - ``handoff_timeouts``   offers that never became pullable within
      ``handoff_timeout_steps``
    - ``handoff_recomputes`` requests that fell back to a full
      colocated recompute (dropped/corrupt/timed-out/orphaned offer)
    - ``rerolls``            replica role flips (prefill <-> decode)
      under sustained queue-wait vs ITL pressure imbalance

    Client-visible latency/goodput lives on the router's own
    :class:`ServingMetrics`, not here — this bag is pure fleet-control
    accounting."""

    def __init__(self):
        self.counters: dict[str, int] = {
            "dispatched": 0, "failovers": 0, "replayed_requests": 0,
            "replayed_tokens": 0, "shed": 0, "ejections": 0,
            "breaker_opens": 0, "probes": 0,
            "snapshot_restores": 0, "snapshot_fallbacks": 0,
            "recovery_restored_tokens": 0, "recovery_replayed_tokens": 0,
            "duplicates_suppressed": 0, "stale_epoch_discarded": 0,
            "lease_expirations": 0,
            "handoff_prefills": 0, "handoff_offers": 0,
            "handoff_bytes": 0, "handoff_pulls": 0,
            "handoff_commits": 0, "handoff_corrupt": 0,
            "handoff_timeouts": 0, "handoff_recomputes": 0,
            "rerolls": 0,
        }
        # time-to-first-recovered-token samples: ejection -> the first
        # token beyond the request's pre-failover stream
        self.recovery_latency_s: list[float] = []

    def bump(self, key: str, n: int = 1) -> None:
        self.counters[key] += n

    def observe_recovery(self, dt: float) -> None:
        self.recovery_latency_s.append(float(dt))

    def summary(self) -> dict:
        return {**self.counters,
                "recovery_ttfrt_p50_s": percentile(
                    self.recovery_latency_s, 50),
                "recovery_ttfrt_p99_s": percentile(
                    self.recovery_latency_s, 99)}


class ServingMetrics:
    def __init__(self, clock=None):
        self._clock = clock if clock is not None else time.monotonic
        self._arrival: dict[str, float] = {}
        self._first_token: dict[str, float] = {}
        self._last_token: dict[str, float] = {}
        self._n_tokens: dict[str, int] = {}
        self._itl: list[float] = []
        self._itl_by_rid: dict[str, list[float]] = {}
        self._finish_reason: dict[str, str | None] = {}
        # SLO thresholds for goodput_at_slo in summary() (set_slo);
        # None = that dimension unconstrained
        self.slo_ttft_s: float | None = None
        self.slo_itl_s: float | None = None
        self._queue_depth: list[int] = []
        self._pool_util: list[float] = []
        self._finished = 0
        self._preemptions = 0
        self._start = None
        self._end = None
        self._admit_t: dict[str, float] = {}
        self._queue_wait: list[float] = []
        # disaggregated serving (SERVING.md "Disaggregated serving"):
        # per-request phase timestamps for ttft_breakdown() — when a
        # prefill-role replica finished the prompt (the KV handoff
        # starts) and when the pulled KV landed on a decode replica.
        # Colocated requests never touch these dicts, so their TTFT
        # attributes entirely to queue-wait + prefill-compute.
        self._prefill_done_t: dict[str, float] = {}
        self._handoff_admit_t: dict[str, float] = {}
        # failure-outcome counters (typed error surface, SERVING.md):
        # rejected_quota / rejected_infeasible are AdmissionShedError
        # sheds (tenant quota exhausted / deadline infeasible), "shed"
        # counts terminal shed outcomes (brownout level 3 + fleet)
        self.counters: dict[str, int] = {
            "rejected_queue_full": 0, "rejected_too_large": 0,
            "rejected_quota": 0, "rejected_infeasible": 0,
            "shed": 0,
            "timed_out": 0, "quarantined": 0, "preempted_limit": 0,
            "drained": 0, "injected": 0,
            # crash-consistent snapshots (serving/snapshot.py):
            # engine-side restore/save outcomes; the store's own
            # capture counters are mirrored in via on_snapshot_stats
            "snapshot_restores": 0, "snapshot_restored_tokens": 0,
            "snapshot_restore_failed": 0, "snapshot_restore_corrupt": 0,
            "snapshot_saves": 0,
            # disaggregated serving (engine side): finished-prefill KV
            # exports published to the handoff outbox
            "handoff_exports": 0,
        }
        # prefix-cache accounting (SERVING.md "Prefix caching"):
        # per-admission token totals accumulate here; the pool's page
        # counters (lookups/hits/evictions/COW) are mirrored in by the
        # engine each step
        self._prefill_tokens = 0
        self._prefill_cached_tokens = 0
        self._prefix_counters: dict[str, int] = {}
        # KV tiering (SERVING.md "KV tiering & traffic harness"):
        # restored tokens are the host-tier slice of the cached tokens
        # above (they skipped recompute but paid restore bytes); the
        # tier's byte gauges are mirrored in from HostTier.stats() each
        # step so summary()/render_prometheus carry spilled_bytes /
        # restored_bytes / host_pool_bytes without a second scrape
        self.host_tier_enabled = 0
        self._prefill_restored_tokens = 0
        self._tier_stats: dict[str, int] = {}
        # int8 KV-cache quantization (SERVING.md "Quantized KV & weights"):
        # the flag gauge plus a running max over per-prefill absmax scales —
        # scale_max/2 bounds the worst-case dequant error of any cached
        # element, the number an operator alerts on
        self.kv_quant_enabled = 0
        self.kv_quant_scale_max = 0.0
        # speculative decoding (SERVING.md "Speculative decoding"):
        # draft/accept token totals, drafter hit counts (calls that
        # proposed >= 1 token), and a per-draft-length accept histogram
        # {n_draft: [accepted_sum, verify_steps]} for the profiler's
        # accept-rate-by-length report
        self.spec_enabled = 0
        self._spec_draft_tokens = 0
        self._spec_accepted_tokens = 0
        self._spec_draft_calls = 0
        self._spec_draft_hits = 0
        self._spec_hist: dict[int, list[int]] = {}
        # chunked prefill / mixed steps (SERVING.md "Chunked prefill &
        # mixed steps"): per-step mixed-batch composition — how many
        # prefill-chunk tokens and decode slots shared each mixed
        # dispatch, how many chunks were cut in total, and how many
        # partially-prefilled requests were in flight at the last step.
        # Schema-stable zeros with chunking off.
        self.chunked_enabled = 0
        # crash-consistent snapshots (serving/snapshot.py): the flag
        # gauge plus a mirror of SnapshotStore.stats() refreshed at
        # each capture — schema-stable zeros with snapshots off
        self.snapshots_enabled = 0
        self._snapshot_stats: dict[str, int] = {}
        # multi-tenant LoRA serving (SERVING.md "Multi-tenant LoRA
        # serving"): the flag gauge plus a mirror of AdapterPool.stats()
        # refreshed each step — the lora_* keys become the
        # paddle_serving_lora_* Prometheus family; schema-stable zeros
        # with LoRA off
        self.lora_enabled = 0
        self._lora_stats: dict = {}
        # tensor parallelism (SERVING.md "Tensor-parallel serving"): the
        # TP degree gauge (1 == single-device engine) and the per-shard
        # KV footprint per cached token — the tp_* keys become the
        # paddle_serving_tp_* Prometheus family via render_prometheus
        self.tp_degree = 1
        self.tp_shard_kv_bytes_per_token = 0
        # pipeline parallelism (SERVING.md "Pipeline-parallel serving"):
        # the pp degree, mixed-step microbatch wave count, and the
        # schedule's idle-stage fraction — the pp_* keys become the
        # paddle_serving_pp_* Prometheus family; schema-stable
        # 1/1/0.0 on a non-pipelined engine
        self.pp_degree = 1
        self.pp_waves = 1
        self.pipeline_bubble_frac = 0.0
        self._mixed_steps = 0
        self._chunk_tokens = 0
        self._chunks_dispatched = 0
        self._chunk_prefill_tokens_last = 0
        self._chunk_decode_slots_last = 0
        self._chunks_in_flight_last = 0
        # SLO-aware overload control (SERVING.md "Overload control &
        # tenant fairness"): the fair/brownout flag gauges, the current
        # brownout level + per-level step occupancy + transition count,
        # and per-tenant / per-priority request attribution — tenants
        # and priorities arrive via on_arrival/on_shed, and summary()
        # flattens them to tenant{t}_* / shed_priority{p} keys so the
        # Prometheus page carries the per-tenant view for free
        self.fair_enabled = 0
        self.brownout_enabled = 0
        self._brownout_level = 0
        self._brownout_steps: dict[int, int] = {1: 0, 2: 0, 3: 0}
        self._brownout_transitions = 0
        self._tenant: dict[str, int] = {}
        self._priority: dict[str, int] = {}
        self._shed_by_priority: dict[int, int] = {}
        self._shed_by_tenant: dict[int, int] = {}

    def now(self) -> float:
        return self._clock()

    # ---- request lifecycle ----

    def on_arrival(self, rid: str, tenant: int = 0,
                   priority: int = 0) -> None:
        t = self.now()
        if self._start is None:
            self._start = t
        self._arrival[rid] = t
        self._tenant[rid] = int(tenant)
        self._priority[rid] = int(priority)

    def on_token(self, rid: str) -> None:
        t = self.now()
        if rid not in self._first_token:
            self._first_token[rid] = t
        else:
            gap = t - self._last_token[rid]
            self._itl.append(gap)
            self._itl_by_rid.setdefault(rid, []).append(gap)
        self._last_token[rid] = t
        self._n_tokens[rid] = self._n_tokens.get(rid, 0) + 1
        self._end = t

    def on_finish(self, rid: str, reason: str | None = None) -> None:
        """Terminal transition; ``reason`` (the finish_reason) feeds
        goodput — only normal finishes (stop/length, or legacy ``None``)
        can count as good requests."""
        self._finished += 1
        self._finish_reason[rid] = reason
        self._end = self.now()

    def on_preemption(self) -> None:
        self._preemptions += 1

    def on_admit(self, rid: str) -> None:
        """First admission of a request: records its queue wait
        (re-admissions after preemption are not new queue waits)."""
        if rid in self._admit_t or rid not in self._arrival:
            return
        t = self.now()
        self._admit_t[rid] = t
        self._queue_wait.append(t - self._arrival[rid])

    def on_prefill_complete(self, rid: str) -> None:
        """Disaggregated serving: the prefill phase finished (the
        prefill-role replica published the request's KV for handoff).
        First call wins — a retried handoff keeps the original mark."""
        if rid not in self._prefill_done_t:
            self._prefill_done_t[rid] = self.now()

    def on_handoff_landed(self, rid: str) -> None:
        """Disaggregated serving: the pulled KV was injected and the
        request re-admitted on a decode-role replica. First call wins,
        so re-pulls after a decode-replica death keep the original
        transfer latency."""
        if rid not in self._handoff_admit_t:
            self._handoff_admit_t[rid] = self.now()

    def ttft_breakdown(self) -> dict:
        """Split each request's TTFT into the three phases the disagg
        A/B attributes cost to: queue-wait (arrival -> first
        admission), prefill-compute (admission -> prefill finished),
        and handoff-transfer (prefill finished -> first token, i.e. the
        KV offer/pull/re-admission plus the decode replica's first
        step). Colocated requests have no prefill-done mark, so their
        compute span runs to the first token and handoff is 0 —
        schema-stable across both serving modes."""
        qw: list[float] = []
        pf: list[float] = []
        ho: list[float] = []
        for rid, t1 in self._first_token.items():
            t0 = self._arrival.get(rid)
            ta = self._admit_t.get(rid)
            if t0 is None or ta is None:
                continue
            qw.append(ta - t0)
            td = self._prefill_done_t.get(rid)
            if td is not None:
                pf.append(max(td - ta, 0.0))
                ho.append(max(t1 - td, 0.0))
            else:
                pf.append(max(t1 - ta, 0.0))
                ho.append(0.0)
        return {
            "ttft_queue_wait_p50_s": percentile(qw, 50),
            "ttft_queue_wait_p99_s": percentile(qw, 99),
            "ttft_prefill_p50_s": percentile(pf, 50),
            "ttft_prefill_p99_s": percentile(pf, 99),
            "ttft_handoff_p50_s": percentile(ho, 50),
            "ttft_handoff_p99_s": percentile(ho, 99),
        }

    def on_reject(self, kind: str) -> None:
        """An add_request rejection: kind is 'queue_full' or 'too_large'."""
        self.counters[f"rejected_{kind}"] += 1

    def on_outcome(self, finish_reason: str) -> None:
        """Count an abnormal terminal outcome by its finish_reason."""
        key = {"timeout": "timed_out", "nonfinite": "quarantined",
               "preempted_limit": "preempted_limit", "preempted": "drained",
               "injected": "injected", "shed": "shed"}.get(finish_reason)
        if key is not None:
            self.counters[key] += 1

    # ---- overload control (SERVING.md "Overload control & tenant
    # fairness") ----

    def set_fair(self, enabled: bool) -> None:
        """Arm the fair_enabled gauge (int, for Prometheus export)."""
        self.fair_enabled = int(bool(enabled))

    def set_brownout(self, enabled: bool) -> None:
        """Arm the brownout_enabled gauge (int, for Prometheus)."""
        self.brownout_enabled = int(bool(enabled))

    def on_brownout_level(self, level: int) -> None:
        """One engine step spent at ``level`` (0 = normal service) —
        feeds the current-level gauge and the per-level occupancy
        counters the bench reports as brownout-level occupancy."""
        self._brownout_level = int(level)
        if level in self._brownout_steps:
            self._brownout_steps[level] += 1

    def on_brownout_transition(self, old: int, new: int) -> None:
        self._brownout_transitions += 1

    def on_shed(self, tenant: int = 0, priority: int = 0) -> None:
        """One shed decision (admission quota/infeasibility or a
        brownout level-3 queue shed), attributed to its tenant and
        priority class — the shed-by-priority breakdown the fairness
        bench reports."""
        self._shed_by_priority[int(priority)] = (
            self._shed_by_priority.get(int(priority), 0) + 1)
        self._shed_by_tenant[int(tenant)] = (
            self._shed_by_tenant.get(int(tenant), 0) + 1)

    def tenant_of(self, rid: str) -> int:
        return self._tenant.get(rid, 0)

    def priority_of(self, rid: str) -> int:
        return self._priority.get(rid, 0)

    def per_tenant(self) -> dict[int, dict]:
        """Per-tenant latency/outcome view: {tenant: {"arrived",
        "finished", "ttft_p50_s", "ttft_p99_s", "shed"}} — finished
        counts normal finishes only (stop/length/legacy None), sheds
        count both admission sheds and terminal shed outcomes. This is
        what the fairness bench ranks arms by (cold-tenant p99 TTFT)."""
        tenants = (set(self._tenant.values())
                   | set(self._shed_by_tenant))
        out: dict[int, dict] = {}
        for t in sorted(tenants):
            rids = [r for r, tt in self._tenant.items() if tt == t]
            ttft = [self._first_token[r] - self._arrival[r]
                    for r in rids
                    if r in self._first_token and r in self._arrival]
            finished = sum(
                1 for r in rids
                if self._finish_reason.get(r, "")
                in (None, "stop", "length"))
            out[t] = {
                "arrived": len(rids),
                "finished": finished,
                "ttft_p50_s": percentile(ttft, 50),
                "ttft_p99_s": percentile(ttft, 99),
                "shed": self._shed_by_tenant.get(t, 0),
            }
        return out

    def shed_by_priority(self) -> dict[int, int]:
        return dict(self._shed_by_priority)

    def on_prefill(self, cached_tokens: int, total_tokens: int,
                   restored_tokens: int = 0) -> None:
        """One admission's prefill accounting: ``cached_tokens`` of the
        ``total_tokens`` context were served from the prefix cache (the
        engine only ran the suffix), ``restored_tokens`` of THOSE came
        back from the host spill tier. Feeds ``cache_hit_rate`` and the
        tier hit-rate breakdown."""
        self._prefill_tokens += total_tokens
        self._prefill_cached_tokens += cached_tokens
        self._prefill_restored_tokens += restored_tokens

    def on_prefix_counters(self, counters: dict) -> None:
        """Mirror the pool's prefix-cache page counters (lookups, hits,
        partial hits, evictions, COW copies) into the summary."""
        self._prefix_counters = dict(counters)

    # ---- KV tiering (SERVING.md "KV tiering & traffic harness") ----

    def set_host_tier(self, enabled: bool) -> None:
        """Arm the host_tier_enabled gauge (int, for Prometheus)."""
        self.host_tier_enabled = int(bool(enabled))

    def on_tier_stats(self, stats: dict) -> None:
        """Mirror the host tier's byte/page gauges (HostTier.stats())
        into the summary — called by the engine once per step."""
        self._tier_stats = dict(stats)

    def tier_hit_rates(self) -> dict:
        """Where prefill context tokens were served from: ``hbm``
        (prefix-cache pages already resident), ``host`` (restored from
        the spill tier), ``miss`` (recomputed). The three sum to 1 once
        any prefill ran; restored tokens are cached tokens, so
        hbm + host == cache_hit_rate."""
        t = self._prefill_tokens
        if t == 0:
            return {"hbm": 0.0, "host": 0.0, "miss": 0.0}
        host = self._prefill_restored_tokens / t
        hbm = (self._prefill_cached_tokens
               - self._prefill_restored_tokens) / t
        return {"hbm": hbm, "host": host, "miss": 1.0 - hbm - host}

    # ---- SLO goodput (ROADMAP item 5) ----

    def set_slo(self, ttft_p99_s: float | None = None,
                itl_p99_s: float | None = None) -> None:
        """Arm the SLO thresholds ``summary()`` scores goodput against.
        ``None`` leaves a dimension unconstrained."""
        self.slo_ttft_s = ttft_p99_s
        self.slo_itl_s = itl_p99_s

    def goodput_at_slo(self, ttft_p99_s: float | None = None,
                       itl_p99_s: float | None = None) -> float:
        """Requests/s that finished normally AND met the SLOs.

        A request is *good* when (a) its finish reason is a normal stop
        (``stop``/``length``; legacy callers that never passed a reason
        count too), (b) it emitted a first token, (c) TTFT <= the TTFT
        SLO, and (d) the p99 of its own inter-token gaps <= the ITL SLO
        (requests with < 2 tokens have no gaps and trivially pass).
        ``None`` SLOs are unconstrained. Denominator is the same wall
        time ``tokens_per_s`` uses; 0.0 before any time has passed.
        """
        wall = ((self._end - self._start)
                if self._start is not None and self._end is not None
                else 0.0)
        if wall <= 0:
            return 0.0
        good = 0
        for rid, reason in self._finish_reason.items():
            if reason not in (None, "stop", "length"):
                continue
            if rid not in self._first_token or rid not in self._arrival:
                continue
            ttft = self._first_token[rid] - self._arrival[rid]
            if ttft_p99_s is not None and ttft > ttft_p99_s:
                continue
            if itl_p99_s is not None:
                gaps = self._itl_by_rid.get(rid, [])
                if gaps and percentile(gaps, 99) > itl_p99_s:
                    continue
            good += 1
        return good / wall

    # ---- int8 KV quantization (SERVING.md "Quantized KV & weights") ----

    def set_kv_quant(self, enabled: bool) -> None:
        """Arm the kv_quant_enabled gauge (int, so Prometheus export —
        which skips non-numeric values — renders it)."""
        self.kv_quant_enabled = int(bool(enabled))

    def on_kv_quant_scale(self, scale_max: float) -> None:
        """Fold one prefill's max absmax scale into the running max."""
        self.kv_quant_scale_max = max(self.kv_quant_scale_max,
                                      float(scale_max))

    # ---- speculative decoding (SERVING.md "Speculative decoding") ----

    def set_spec(self, enabled: bool) -> None:
        """Arm the spec_enabled gauge (int, for Prometheus export)."""
        self.spec_enabled = int(bool(enabled))

    def on_spec_draft(self, proposed: int) -> None:
        """One drafter call for one slot: ``proposed`` tokens offered
        (0 = the drafter had nothing — the slot decodes normally)."""
        self._spec_draft_calls += 1
        if proposed > 0:
            self._spec_draft_hits += 1

    def on_spec_verify(self, drafted: int, accepted: int) -> None:
        """One slot's verify outcome: ``accepted`` of ``drafted`` draft
        tokens matched the engine's own samples (the step emitted
        accepted + 1 tokens before any eos/length truncation)."""
        self._spec_draft_tokens += drafted
        self._spec_accepted_tokens += accepted
        h = self._spec_hist.setdefault(drafted, [0, 0])
        h[0] += accepted
        h[1] += 1

    # ---- chunked prefill (SERVING.md "Chunked prefill & mixed steps") --

    def set_chunked(self, enabled: bool) -> None:
        """Arm the chunked_enabled gauge (int, for Prometheus export)."""
        self.chunked_enabled = int(bool(enabled))

    # ---- crash-consistent snapshots (serving/snapshot.py) ----

    def set_snapshots(self, enabled: bool) -> None:
        """Arm the snapshots_enabled gauge (int, for Prometheus)."""
        self.snapshots_enabled = int(bool(enabled))

    # ---- tensor parallelism (serving/parallel.py) ----

    def set_tp(self, tp: int, shard_kv_bytes_per_token: int = 0) -> None:
        """Arm the TP gauges: the engine's TP degree and the per-DEVICE
        KV bytes one cached token costs (== the full figure at tp=1)."""
        self.tp_degree = int(tp)
        self.tp_shard_kv_bytes_per_token = int(shard_kv_bytes_per_token)

    def set_pp(self, pp: int, waves: int = 1,
               bubble_frac: float = 0.0) -> None:
        """Arm the pipeline-parallel gauges: the pp degree, the mixed
        step's microbatch wave count, and the pipeline schedule's
        idle-stage (bubble) fraction ``(pp-1)/(waves+pp-1)``."""
        self.pp_degree = int(pp)
        self.pp_waves = int(waves)
        self.pipeline_bubble_frac = float(bubble_frac)

    def on_snapshot_stats(self, stats: dict) -> None:
        """Mirror the snapshot store's capture gauges
        (SnapshotStore.stats()) into the summary — called by the
        engine after each periodic capture."""
        self._snapshot_stats = dict(stats)

    # ---- multi-tenant LoRA (SERVING.md "Multi-tenant LoRA serving") --

    def set_lora(self, enabled: bool) -> None:
        """Arm the lora_enabled gauge (int, for Prometheus export)."""
        self.lora_enabled = int(bool(enabled))

    def on_lora_stats(self, stats: dict) -> None:
        """Mirror the adapter pool's gauges (AdapterPool.stats()) into
        the summary — called by the engine once per step. Keys land
        under a ``lora_`` prefix so render_prometheus emits them as the
        ``paddle_serving_lora_*`` family."""
        self._lora_stats = dict(stats)

    def on_mixed_step(self, prefill_tokens: int, decode_slots: int,
                      chunk_slots: int, in_flight: int) -> None:
        """One mixed-step dispatch: ``prefill_tokens`` prompt-chunk
        tokens across ``chunk_slots`` slots shared the program with
        ``decode_slots`` decoding/verifying slots; ``in_flight`` is the
        number of partially-prefilled requests resident after planning
        (slots mid-prompt, whether or not they got a chunk this step)."""
        self._mixed_steps += 1
        self._chunk_tokens += prefill_tokens
        self._chunks_dispatched += chunk_slots
        self._chunk_prefill_tokens_last = prefill_tokens
        self._chunk_decode_slots_last = decode_slots
        self._chunks_in_flight_last = in_flight

    def spec_accept_rate(self) -> float:
        """Fraction of drafted tokens accepted by the verify step."""
        if self._spec_draft_tokens == 0:
            return 0.0
        return self._spec_accepted_tokens / self._spec_draft_tokens

    def spec_draft_hit_rate(self) -> float:
        """Fraction of drafter calls that proposed at least one token."""
        if self._spec_draft_calls == 0:
            return 0.0
        return self._spec_draft_hits / self._spec_draft_calls

    def spec_accept_histogram(self) -> dict[int, dict]:
        """Accept stats keyed by draft length: {n_draft: {"steps",
        "accepted_mean", "accept_rate"}} — the profiler's per-length
        report (tools/profile_serving.py --spec)."""
        out = {}
        for n, (acc, steps) in sorted(self._spec_hist.items()):
            out[n] = {"steps": steps,
                      "accepted_mean": acc / steps if steps else 0.0,
                      "accept_rate": acc / (n * steps)
                      if n and steps else 0.0}
        return out

    def cache_hit_rate(self) -> float:
        """Fraction of prefill context tokens served from cached pages."""
        if self._prefill_tokens == 0:
            return 0.0
        return self._prefill_cached_tokens / self._prefill_tokens

    # ---- per-step gauges ----

    def on_step(self, queue_depth: int, pool_utilization: float) -> None:
        self._queue_depth.append(queue_depth)
        self._pool_util.append(pool_utilization)

    # ---- aggregation ----

    def ttfts(self) -> list[float]:
        return [self._first_token[r] - self._arrival[r]
                for r in self._first_token if r in self._arrival]

    def tpots(self) -> list[float]:
        out = []
        for r, n in self._n_tokens.items():
            if n > 1:
                out.append((self._last_token[r] - self._first_token[r])
                           / (n - 1))
        return out

    @property
    def total_tokens(self) -> int:
        return sum(self._n_tokens.values())

    def summary(self) -> dict:
        from .lora import AdapterPool as _AdapterPool
        from .snapshot import SnapshotStore as _SnapshotStore
        from .tiering import HostTier as _HostTier
        ttft = self.ttfts()
        tpot = self.tpots()
        tier_rates = self.tier_hit_rates()
        wall = ((self._end - self._start)
                if self._start is not None and self._end is not None else 0.0)
        return {
            "requests_finished": self._finished,
            "tokens_generated": self.total_tokens,
            "wall_s": wall,
            "tokens_per_s": (self.total_tokens / wall) if wall > 0 else 0.0,
            "ttft_p50_s": percentile(ttft, 50),
            "ttft_p99_s": percentile(ttft, 99),
            "tpot_mean_s": (sum(tpot) / len(tpot)) if tpot else 0.0,
            "itl_p50_s": percentile(self._itl, 50),
            "itl_p99_s": percentile(self._itl, 99),
            "preemptions": self._preemptions,
            "queue_depth_max": max(self._queue_depth, default=0),
            "queue_depth_mean": (sum(self._queue_depth)
                                 / len(self._queue_depth)
                                 if self._queue_depth else 0.0),
            "kv_util_mean": (sum(self._pool_util) / len(self._pool_util)
                             if self._pool_util else 0.0),
            "kv_util_peak": max(self._pool_util, default=0.0),
            "queue_wait_p50_s": percentile(self._queue_wait, 50),
            "queue_wait_p99_s": percentile(self._queue_wait, 99),
            # TTFT attribution (SERVING.md "Disaggregated serving"):
            # queue-wait / prefill-compute / handoff-transfer — always
            # present; handoff percentiles are 0 for colocated serving
            **self.ttft_breakdown(),
            "rejected": (self.counters["rejected_queue_full"]
                         + self.counters["rejected_too_large"]),
            "cache_hit_rate": self.cache_hit_rate(),
            "prefill_tokens": self._prefill_tokens,
            "prefill_cached_tokens": self._prefill_cached_tokens,
            "goodput_at_slo": self.goodput_at_slo(self.slo_ttft_s,
                                                  self.slo_itl_s),
            # always present (schema-stable for Prometheus scrapers);
            # err_bound = scale_max/2 is the worst-case |dequant - true|
            # of any element in the int8 cache
            "kv_quant_enabled": self.kv_quant_enabled,
            "kv_quant_scale_max": self.kv_quant_scale_max,
            "kv_quant_err_bound": self.kv_quant_scale_max / 2.0,
            # speculative decoding gauges/counters (schema-stable: zeros
            # with speculation off)
            "spec_enabled": self.spec_enabled,
            "spec_draft_tokens_total": self._spec_draft_tokens,
            "spec_accepted_tokens_total": self._spec_accepted_tokens,
            "spec_accept_rate": self.spec_accept_rate(),
            "spec_draft_hit_rate": self.spec_draft_hit_rate(),
            # chunked prefill / mixed-step composition (schema-stable:
            # zeros with chunking off)
            "chunked_enabled": self.chunked_enabled,
            "mixed_steps": self._mixed_steps,
            "chunk_tokens_total": self._chunk_tokens,
            "chunks_dispatched_total": self._chunks_dispatched,
            "chunk_prefill_tokens_last": self._chunk_prefill_tokens_last,
            "chunk_decode_slots_last": self._chunk_decode_slots_last,
            "chunks_in_flight": self._chunks_in_flight_last,
            # KV tiering (schema-stable: zeros with the tier off).
            # tier_hit_rate == cache_hit_rate (restored tokens ARE
            # cached tokens); the hbm/host/miss split is the breakdown.
            "host_tier_enabled": self.host_tier_enabled,
            "prefill_restored_tokens": self._prefill_restored_tokens,
            "tier_hit_rate": self.cache_hit_rate(),
            "tier_hbm_hit_rate": tier_rates["hbm"],
            "tier_host_hit_rate": tier_rates["host"],
            "tier_miss_rate": tier_rates["miss"],
            **{**_HostTier.zero_stats(), **self._tier_stats},
            # crash-consistent snapshots (schema-stable: zeros with
            # snapshotting off; the store's keys are snapshot_-prefixed)
            "snapshots_enabled": self.snapshots_enabled,
            **{**_SnapshotStore.zero_stats(), **self._snapshot_stats},
            # multi-tenant LoRA serving (schema-stable: zeros with LoRA
            # off). AdapterPool.stats() keys land under a lora_ prefix
            # — the paddle_serving_lora_* Prometheus family — so pool
            # gauges like "capacity" can never shadow a summary key.
            "lora_enabled": self.lora_enabled,
            **{(k if k.startswith("lora_") else "lora_" + k): v
               for k, v in {**_AdapterPool.zero_stats(),
                            **self._lora_stats}.items()},
            # tensor parallelism (schema-stable: tp_degree 1 on a
            # single-device engine) — the paddle_serving_tp_* family
            "tp_degree": self.tp_degree,
            "tp_shard_kv_bytes_per_token": self.tp_shard_kv_bytes_per_token,
            # pipeline parallelism (schema-stable: pp_degree 1, bubble
            # 0.0 on an unstaged engine) — the paddle_serving_pp_* family
            "pp_degree": self.pp_degree,
            "pp_waves": self.pp_waves,
            "pipeline_bubble_frac": self.pipeline_bubble_frac,
            # SLO-aware overload control (schema-stable zeros when fair
            # scheduling / the brownout ladder are off); the per-tenant
            # and per-priority flattenings below are dynamic keys, like
            # the pool counters — present once a tenant/priority is seen
            "fair_enabled": self.fair_enabled,
            "brownout_enabled": self.brownout_enabled,
            "brownout_level": self._brownout_level,
            "brownout_transitions": self._brownout_transitions,
            "brownout_level1_steps": self._brownout_steps.get(1, 0),
            "brownout_level2_steps": self._brownout_steps.get(2, 0),
            "brownout_level3_steps": self._brownout_steps.get(3, 0),
            **{f"tenant{t}_{k}": v
               for t, d in self.per_tenant().items()
               for k, v in d.items()},
            **{f"shed_priority{p}": n
               for p, n in sorted(self._shed_by_priority.items())},
            # pool counters live under prefix_* so they can never
            # shadow a summary key (the pool already uses that prefix
            # for most of them — normalise the stragglers)
            **{(k if k.startswith("prefix_") else "prefix_" + k): v
               for k, v in self._prefix_counters.items()},
            **self.counters,
        }
