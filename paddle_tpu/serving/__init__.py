"""paddle_tpu.serving — continuous-batching LLM serving on TPU.

A paged KV-cache pool (PagedAttention, SOSP '23) plus an
iteration-level continuous-batching engine (Orca, OSDI '22) whose
decode step is one compiled program over a fixed slot axis — request
churn changes array values, never shapes, so nothing ever retraces.
See SERVING.md for the design and the determinism contract.

    from paddle_tpu.serving import ServingEngine, SamplingParams
    eng = ServingEngine(model, num_pages=64, page_size=16, max_slots=4)
    rid = eng.add_request(prompt_ids, max_new_tokens=32, eos_token_id=2)
    for ev in eng.stream():
        print(ev["rid"], ev["token"])
"""

from .engine import ServingEngine
from .errors import (EngineDrainingError, QueueFullError,
                     RequestTooLargeError, SchedulerStalledError,
                     ServingError)
from .kv_cache import KVCachePool, PoolExhaustedError, PrefixMatch
from .metrics import ServingMetrics, percentile
from .scheduler import (FINISHED, PREEMPTED, RUNNING, WAITING, Request,
                        SamplingParams, Scheduler)

__all__ = [
    "ServingEngine", "KVCachePool", "PoolExhaustedError", "PrefixMatch",
    "ServingMetrics",
    "percentile", "Request", "SamplingParams", "Scheduler",
    "WAITING", "RUNNING", "PREEMPTED", "FINISHED",
    "ServingError", "QueueFullError", "RequestTooLargeError",
    "SchedulerStalledError", "EngineDrainingError",
]
