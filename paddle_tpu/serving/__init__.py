"""paddle_tpu.serving — continuous-batching LLM serving on TPU.

A paged KV-cache pool (PagedAttention, SOSP '23) plus an
iteration-level continuous-batching engine (Orca, OSDI '22) whose
decode step is one compiled program over a fixed slot axis — request
churn changes array values, never shapes, so nothing ever retraces.
See SERVING.md for the design and the determinism contract.

    from paddle_tpu.serving import ServingEngine, SamplingParams
    eng = ServingEngine(model, num_pages=64, page_size=16, max_slots=4)
    rid = eng.add_request(prompt_ids, max_new_tokens=32, eos_token_id=2)
    for ev in eng.stream():
        print(ev["rid"], ev["token"])

For fault tolerance, N replicas go behind a :class:`FleetRouter`
(fleet.py — SERVING.md "Engine fleet & failover"): health-checked
least-loaded routing with prefix-cache affinity, circuit-broken
placement, and deterministic failover replay with exactly-once client
streams.
"""

from .engine import BrownoutConfig, ServingEngine
from .errors import (AdmissionShedError, EngineDrainingError,
                     FleetOverloadedError, QueueFullError,
                     ReplicaSpawnError, RequestTooLargeError,
                     SchedulerStalledError, ServingError, StaleEpochError,
                     TPConfigError, TransportError)
from .fleet import FleetRequest, FleetRouter
from .transport import (ChaosTransport, EngineServer, LoopbackTransport,
                        Message, Transport, deterministic_jitter)
from .transport_socket import FrameChaos, FrameDecoder, SocketTransport
from .kv_cache import KVCachePool, PoolExhaustedError, PrefixMatch
from .lora import (AdapterExhaustedError, AdapterPool,
                   AdapterUnavailableError, LoRAAdapter)
from .metrics import FleetMetrics, ServingMetrics, percentile
from .parallel import (TPContext, collective_counts, partition_devices,
                       validate_tp_config)
from .scheduler import (FINISHED, PREEMPTED, RUNNING, WAITING, Request,
                        SamplingParams, Scheduler)
from .snapshot import (RequestSnapshot, SnapshotStore,
                       load_engine_snapshot, save_engine_snapshot,
                       snapshot_from_wire, snapshot_to_wire)
from .speculative import DraftProposer, NgramDrafter, SpeculativeConfig
from .tiering import HostTier
from .workload import (Workload, WorkloadRequest, WorkloadSpec,
                       heavy_tail_workload, long_prompt_workload,
                       make_workload, overload_workload)

__all__ = [
    "ServingEngine", "BrownoutConfig",
    "KVCachePool", "PoolExhaustedError", "PrefixMatch",
    "ServingMetrics", "FleetMetrics",
    "FleetRouter", "FleetRequest",
    "percentile", "Request", "SamplingParams", "Scheduler",
    "WAITING", "RUNNING", "PREEMPTED", "FINISHED",
    "SpeculativeConfig", "DraftProposer", "NgramDrafter",
    "HostTier",
    "AdapterPool", "LoRAAdapter",
    "AdapterExhaustedError", "AdapterUnavailableError",
    "SnapshotStore", "RequestSnapshot",
    "save_engine_snapshot", "load_engine_snapshot",
    "snapshot_to_wire", "snapshot_from_wire",
    "Workload", "WorkloadRequest", "WorkloadSpec", "heavy_tail_workload",
    "long_prompt_workload", "make_workload", "overload_workload",
    "ServingError", "QueueFullError", "RequestTooLargeError",
    "SchedulerStalledError", "EngineDrainingError", "FleetOverloadedError",
    "TPConfigError", "AdmissionShedError",
    "TransportError", "StaleEpochError", "ReplicaSpawnError",
    "Transport", "LoopbackTransport", "ChaosTransport", "EngineServer",
    "Message", "deterministic_jitter",
    "SocketTransport", "FrameChaos", "FrameDecoder",
    "TPContext", "partition_devices", "validate_tp_config",
    "collective_counts",
]
