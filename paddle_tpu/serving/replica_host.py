"""Process-isolated fleet replicas: ``python -m
paddle_tpu.serving.replica_host`` (SERVING.md "Multi-host serving").

One replica host = one OS process owning one real
:class:`~.engine.ServingEngine` behind an :class:`~.transport.EngineServer`,
speaking the canonical PR-15 wire to the router over a
:class:`~.transport_socket.SocketTransport`. The process builds its
model from a JSON spec (same seed + same config = bitwise-identical
weights in every replica — the determinism contract crosses the
process boundary with no weight shipping), warms the step programs
BEFORE dialing the router (compilation happens outside any lease), and
then runs the host loop::

    pump the socket -> run at most one latched engine step -> repeat

The :class:`~.transport.EngineServer` runs in deferred step mode, so a
burst of retransmitted STEPs can never wedge the process in
back-to-back engine steps and starve its heartbeat acks into a lease
expiry.

Kill semantics (the whole point):

- SIGTERM — the existing preemption guard trips; the host runs the
  engine's drain and streams an unsolicited ``DRAIN_RESULTS``
  (``EngineServer.announce_drain``) so in-flight requests finish or
  classify as ``preempted``, flushes its socket, and exits 143
  (``EXIT_PREEMPTED``).
- SIGKILL — nothing graceful CAN happen, which is the scenario the
  fleet is built for: the router notices pure silence (lease expiry),
  fences the epoch, and replays the dead replica's requests elsewhere
  — snapshot-seeded when a fetched snapshot exists. The router-side
  handle classifies the corpse post-mortem (``signal:SIGKILL``).

The parent-side API is :func:`spawn_fleet` — spawn N hosts on
localhost, wait for their HELLOs, and return a ready
``FleetRouter(transport=SocketTransport(...))`` driving them purely
through the wire — plus :class:`RemoteEngineHandle` (the engine-shaped
stand-in the router holds: pid/addr/post-mortem, no serving-path
calls) and :func:`reap_orphans` (test hygiene: no replica process may
outlive its test).

Spec keys (all optional): ``seed`` (weight seed, default 0),
``config`` (llama_tiny config overrides), ``engine`` (ServingEngine
kwargs, e.g. num_pages/page_size/max_slots/snapshot_interval),
``snapshots`` (bool: give the engine a PRIVATE in-process
SnapshotStore — the router harvests it over the wire via
SNAPSHOT_FETCH, modelling per-host stores that die with the host
unless fetched).

Children inherit ``JAX_PLATFORMS`` (forced to ``cpu`` when unset) and
single-thread BLAS caps from :func:`spawn_fleet`, so a test fleet
stays inside the CI budget.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

__all__ = ["RemoteEngineHandle", "spawn_fleet", "shutdown_fleet",
           "reap_orphans", "build_engine", "serve"]

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# every process this module ever spawned (until reaped) — the test
# fixture sweeps it so no replica can outlive its test
_SPAWNED: list = []


# ---------------------------------------------------------------------------
# parent side: handles + spawn/attach
# ---------------------------------------------------------------------------


class RemoteEngineHandle:
    """The engine-shaped object a router holds for an out-of-process
    replica. ``is_remote`` makes the router skip building a local
    EngineServer (the real one lives in the child, bound to the same
    ``replica:i`` name on the far end of the socket); everything else
    the router touches out-of-band (``pool``, ``snapshot_store``,
    ``flight_recorder``) reads None. What the handle CAN do is classify
    the process's fate — ``post_mortem()`` feeds the router's ejection
    bookkeeping and ``health()``'s ``exit_status``."""

    is_remote = True
    snapshot_store = None
    flight_recorder = None
    pool = None

    def __init__(self, idx: int, proc, addr: str | None = None):
        self.idx = int(idx)
        self.proc = proc
        self.addr = addr            # "ip:port" once connected

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self):
        return self.proc.poll()

    def post_mortem(self) -> str:
        """Classify how the process died: ``signal:SIGKILL`` (and
        friends) for signal deaths, ``preempted:SIGTERM`` for a clean
        guard-drained 143, ``exit:N`` otherwise, ``running`` if it has
        not died at all (a lease can expire on a live-but-wedged
        process — that distinction matters in a post-mortem)."""
        rc = self.proc.poll()
        if rc is None:
            return "running"
        if rc < 0:
            try:
                return f"signal:{signal.Signals(-rc).name}"
            except ValueError:
                return f"signal:{-rc}"
        from ..distributed.fleet.preempt import EXIT_PREEMPTED
        if rc == EXIT_PREEMPTED:
            return "preempted:SIGTERM"
        return f"exit:{rc}"

    def kill(self) -> None:
        self.proc.kill()

    def terminate(self) -> None:
        self.proc.terminate()

    def wait(self, timeout: float | None = None):
        return self.proc.wait(timeout)


def spawn_fleet(n: int, spec: dict | None = None,
                host: str = "127.0.0.1", *,
                router_kwargs: dict | None = None,
                transport_kwargs: dict | None = None,
                spawn_timeout_s: float = 120.0):
    """Spawn ``n`` replica host processes on ``host``, wait for every
    HELLO, and return ``(router, handles)`` — a
    ``FleetRouter(transport=SocketTransport(...))`` already attached to
    the live fleet. Raises :class:`~.errors.ReplicaSpawnError` (after
    killing whatever did spawn) if any child dies first or the barrier
    times out.

    The router's membership knobs default to wall-clock-scaled values
    (a router step over sockets is ~``poll_s``, not a synchronous
    loopback call): lease ~600 steps, heartbeats every 2, drain/shed
    patience in the thousands. Override via ``router_kwargs``."""
    from .fleet import FleetRouter
    from .snapshot import SnapshotStore
    from .transport_socket import SocketTransport

    spec = dict(spec or {})
    tkw = dict(transport_kwargs or {})
    transport = SocketTransport("router", listen=(host, 0), **tkw)
    addr = transport.listen_addr
    env = dict(os.environ)
    # JAX_PLATFORMS inherited; forced to cpu when unset so a spawned
    # test fleet can never grab the real chip by accident
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS") or "cpu"
    for var in ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS",
                "MKL_NUM_THREADS"):
        env.setdefault(var, "1")
    env["PYTHONPATH"] = _REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs, handles = [], []
    try:
        for i in range(n):
            proc = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.serving.replica_host",
                 "--router", f"{addr[0]}:{addr[1]}", "--idx", str(i),
                 "--spec-json", json.dumps(spec)],
                env=env, cwd=_REPO_ROOT)
            _SPAWNED.append(proc)
            procs.append(proc)
            handles.append(RemoteEngineHandle(i, proc))
        transport.wait_peers([f"replica:{i}" for i in range(n)],
                             timeout_s=spawn_timeout_s, procs=procs)
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        transport.close()
        raise
    for h in handles:
        h.addr = transport.peer_addr(f"replica:{h.idx}")
    rkw = dict(router_kwargs or {})
    rkw.setdefault("lease_steps", 600)
    rkw.setdefault("heartbeat_interval", 2)
    rkw.setdefault("shed_patience", 5000)
    rkw.setdefault("drain_patience", 3000)
    rkw.setdefault("snapshot_fetch_interval", 8)
    if spec.get("snapshots") and "snapshot_store" not in rkw:
        # the router-side durable medium the per-host private stores
        # are harvested into — what survives a SIGKILL
        rkw["snapshot_store"] = SnapshotStore()
    router = FleetRouter(handles, transport=transport, **rkw)
    return router, handles


def shutdown_fleet(router, handles, timeout_s: float = 10.0) -> None:
    """Graceful teardown: SIGTERM every live child (its guard drains
    and exits 143), escalate to SIGKILL past ``timeout_s``, close the
    router's transport."""
    for h in handles:
        if h.poll() is None:
            try:
                h.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + float(timeout_s)
    for h in handles:
        if h.poll() is None:
            try:
                h.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                h.kill()
                h.wait(5.0)
    transport = getattr(router, "transport", None)
    if transport is not None and hasattr(transport, "close"):
        transport.close()


def reap_orphans() -> int:
    """SIGKILL every process this module spawned that is still alive,
    and forget them all. Returns how many needed killing — a conftest
    fixture asserts this is 0 after a well-behaved test."""
    killed = 0
    for proc in _SPAWNED:
        if proc.poll() is None:
            killed += 1
            try:
                proc.kill()
                proc.wait(10.0)
            except OSError:
                pass
    _SPAWNED.clear()
    return killed


# ---------------------------------------------------------------------------
# child side: the host process
# ---------------------------------------------------------------------------


def build_engine(spec: dict):
    """Construct the replica's engine from the spec — deterministically:
    ``pt.seed(spec['seed'])`` before init means every replica of the
    same spec holds bitwise-identical weights without any weight
    transfer."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    from .engine import ServingEngine
    from .snapshot import SnapshotStore

    pt.seed(int(spec.get("seed", 0)))
    cfg_kw = dict(spec.get("config") or {})
    cfg_kw.setdefault("mp_axis", None)
    cfg_kw.setdefault("fsdp_axis", None)
    model = LlamaForCausalLM(llama_tiny(**cfg_kw))
    model.eval()
    eng_kw = dict(spec.get("engine") or {})
    eng_kw.setdefault("num_pages", 64)
    eng_kw.setdefault("page_size", 4)
    eng_kw.setdefault("max_slots", 4)
    if spec.get("snapshots"):
        eng_kw.setdefault("snapshot_store", SnapshotStore())
    return ServingEngine(model, **eng_kw)


def serve(idx: int, router_addr: tuple, spec: dict, *,
          drain_timeout_s: float | None = 5.0,
          idle_exit_s: float = 120.0,
          poll_s: float = 0.002) -> int:
    """The host loop. Returns the process exit code (143 after a
    SIGTERM drain, 0 on router-gone idle exit)."""
    from ..distributed.fleet.preempt import EXIT_PREEMPTED
    from .transport import EngineServer
    from .transport_socket import SocketTransport

    engine = build_engine(spec)
    # SIGTERM -> the EXISTING drain guard, armed before the (slow)
    # warm so a preemption during compile still exits cleanly
    guard = engine.attach_preemption_guard()
    engine.warm_programs()      # compile OUTSIDE any lease window
    # warm the advisory read paths too: the first pool.utilization() /
    # audit_pool() call jit-compiles, which would otherwise eat the
    # router's first (timeout-bounded) gauges/introspect query
    pool = getattr(engine, "pool", None)
    if pool is not None:
        pool.utilization()
    audit = getattr(engine, "audit_pool", None)
    if audit is not None:
        audit()
    transport = SocketTransport(
        f"replica:{idx}", connect={"router": router_addr}, poll_s=poll_s)
    server = EngineServer(idx, engine, transport, step_mode="deferred")
    last_routed = time.monotonic()
    step = 0
    try:
        while True:
            step += 1
            transport.tick(step)
            transport.pump()
            if server.pending_step():
                server.run_pending_step()
            if guard.preempted:
                server.announce_drain(timeout_s=drain_timeout_s)
                deadline = time.monotonic() + 5.0
                while (transport.pending_output()
                       and time.monotonic() < deadline):
                    transport.pump()
                return EXIT_PREEMPTED
            if "router" in transport.peers():
                last_routed = time.monotonic()
            elif time.monotonic() - last_routed > idle_exit_s:
                # the router has been gone for a long time: the parent
                # died without killing us — exit instead of orphaning
                return 0
    finally:
        transport.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="paddle_tpu fleet replica host process")
    parser.add_argument("--router", required=True,
                        help="router host:port to dial")
    parser.add_argument("--idx", type=int, required=True,
                        help="replica index (names this endpoint)")
    parser.add_argument("--spec-json", default="{}",
                        help="engine/model spec as a JSON object")
    parser.add_argument("--drain-timeout-s", type=float, default=5.0)
    parser.add_argument("--idle-exit-s", type=float, default=120.0)
    args = parser.parse_args(argv)

    # the environment may pin a TPU platform via sitecustomize: the env
    # var alone is not enough, jax.config must be updated post-import
    # (same move as tests/conftest.py) — BEFORE any backend use
    platform = os.environ.get("JAX_PLATFORMS") or "cpu"
    import jax
    jax.config.update("jax_platforms", platform)

    host, _, port = args.router.rpartition(":")
    spec = json.loads(args.spec_json)
    return serve(args.idx, (host, int(port)), spec,
                 drain_timeout_s=args.drain_timeout_s,
                 idle_exit_s=args.idle_exit_s)


if __name__ == "__main__":
    sys.exit(main())
