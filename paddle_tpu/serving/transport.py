"""Partition-tolerant fleet transport (SERVING.md "Fleet transport &
membership"; ROADMAP item 4).

Every fleet guarantee before this module — exactly-once failover
replay, bounded-replay snapshots, overload control — silently assumed
the router calls its replicas as in-process Python objects: calls never
drop, never duplicate, never arrive from a replica the router already
gave up on. This module breaks that assumption on purpose. ALL
router<->replica traffic becomes typed :class:`Message` values crossing
a :class:`Transport`:

- :class:`LoopbackTransport` delivers synchronously and losslessly —
  the default, reproducing the pre-transport in-process fleet bitwise
  (every existing fleet/snapshot/fairness suite runs unchanged on it).
- :class:`ChaosTransport` is a seeded hostile network: it
  deterministically drops, duplicates, delays (in router steps — the
  fleet's only injectable clock), reorders, corrupts and one- or
  two-way partitions traffic. Partitioned messages are HELD, not
  dropped, and released when the partition heals — which is exactly
  what lets a zombie replica's stale acks arrive after the router has
  ejected it, the scenario epoch fencing exists for.
- :class:`EngineServer` is the replica-side shim: it owns one engine,
  dedups at-least-once delivery (submits by ``(rid, epoch, attempt)``,
  steps by the router's step seqno), tags every reply with the epoch it
  is answering, and retransmits unacknowledged result batches whenever
  the router contacts it — at-least-once send + receiver dedup =
  exactly-once application.

Wire integrity follows the HostTier/snapshot precedent
(serving/tiering.py, serving/snapshot.py): every message body carries a
blake2b-128 digest over its exact serialized bytes, re-verified at
receive — a corrupted payload is dropped and counted
(:class:`~.errors.TransportError`), never consumed. Snapshots ride
messages as :class:`~.snapshot.RequestSnapshot` values whose OWN page
and meta digests are re-verified at receive; a corrupt snapshot is
stripped from the message (counted) and the failover degrades to full
replay — slower, never wrong.

Ordering model: replica->router results (submit replies, step results,
drain results, snapshot data, typed errors) form ONE per-replica
ordered stream with per-batch seqnos — the router applies batches in
seq order, buffers the future, suppresses duplicates, and acks
cumulatively on every message it sends; the server resends unacked
batches whenever it hears from the router. Heartbeat acks are
out-of-band (idempotent gauge refreshes — freshest seqno wins).
Router->replica messages need no stream: each kind is idempotent at
the server by construction.

Fault sites (RESILIENCE.md): ``fleet.transport.send`` and
``fleet.transport.recv`` fire per message with ``ctx['path'] =
"<KIND>:<rid>"`` and support the transport actions ``drop``, ``dup``,
``delay`` (``arg`` = steps) and ``corrupt`` — so a FaultPlan can make
even the loopback wire lossy for one message kind of one request.

The deterministic backoff-jitter helper the fleet circuit breaker and
the heartbeat scheduler share lives here too (:func:`deterministic_jitter`):
a sha256 draw keyed on a caller-chosen string — never wall-clock
entropy, so chaos runs replay bit-identically.
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
from dataclasses import dataclass, field

from ..distributed import fault as _fault
from .errors import (RequestTooLargeError, SchedulerStalledError,
                     ServingError, StaleEpochError, TransportError)
from .scheduler import SamplingParams

__all__ = ["Message", "Transport", "LoopbackTransport", "ChaosTransport",
           "EngineServer", "deterministic_jitter"]


def deterministic_jitter(key: str, bound: int) -> int:
    """Deterministic jitter in ``[0, bound)``: a sha256 draw over a
    caller-chosen key string, never wall-clock entropy — chaos runs
    replay bit-identically. Shared by the fleet circuit breaker's
    backoff (``key = "fleet-jitter:<replica>:<opens>"``) and the
    heartbeat scheduler's phase offset (``key = "fleet-hb:<replica>"``)."""
    if bound <= 1:
        return 0
    h = hashlib.sha256(key.encode()).digest()
    return int.from_bytes(h[:4], "big") % bound


def _jsonable(obj):
    """JSON fallback for numpy scalars riding event/payload dicts."""
    item = getattr(obj, "item", None)
    if item is not None:
        return item()
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        return tolist()
    raise TypeError(f"not wire-serializable: {type(obj).__name__}")


def _encode_body(payload: dict) -> bytes:
    """Canonical wire bytes for a payload dict (sorted keys, compact
    separators) — the exact bytes the digest covers."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      default=_jsonable).encode()


def _body_digest(body: bytes) -> bytes:
    """blake2b-128 over the body bytes — same construction as the
    HostTier/snapshot payload digests (tiering._payload_digest)."""
    h = hashlib.blake2b(digest_size=16)
    h.update(body)
    return h.digest()


@dataclass
class Message:
    """One typed wire message.

    ``body`` is the canonical JSON serialization of the payload;
    ``digest`` is blake2b-128 over those exact bytes, re-verified at
    receive. ``snaps`` carries :class:`RequestSnapshot` values, each
    self-verifying through its own page/meta digests. ``seq`` orders
    the replica->router result stream (0 = unordered); ``epoch`` is the
    replica life the message belongs to — the fence the router checks."""
    kind: str
    src: str
    dst: str
    epoch: int = 0
    seq: int = 0
    rid: str = ""
    body: bytes = b"{}"
    digest: bytes = b""
    snaps: tuple = ()
    msg_id: int = -1          # assigned by the transport at (re)send

    _payload_cache: dict | None = field(default=None, repr=False,
                                        compare=False)

    @classmethod
    def make(cls, kind: str, src: str, dst: str, *, epoch: int = 0,
             seq: int = 0, rid: str = "", payload: dict | None = None,
             snaps: tuple = ()) -> "Message":
        body = _encode_body(payload or {})
        return cls(kind=kind, src=src, dst=dst, epoch=int(epoch),
                   seq=int(seq), rid=str(rid), body=body,
                   digest=_body_digest(body), snaps=tuple(snaps))

    def payload(self) -> dict:
        if self._payload_cache is None:
            self._payload_cache = json.loads(self.body.decode())
        return self._payload_cache

    def verify(self) -> bool:
        """Re-check the body digest — the receive-side integrity gate."""
        return _body_digest(self.body) == self.digest

    @property
    def path(self) -> str:
        """The fault-site / trace path: message kind + request id."""
        return f"{self.kind}:{self.rid}"


class Transport:
    """Message fabric between the router and its replica endpoints.

    Endpoints are named (``"router"``, ``"replica:<i>"``). An endpoint
    binds either a handler (called at delivery — how :class:`EngineServer`
    processes traffic) or an inbox (drained with :meth:`recv` — how the
    router consumes replies). :meth:`tick` advances the transport clock
    in ROUTER STEPS (the fleet's injectable clock); :meth:`pump` runs
    deliveries until quiescent.

    The base class owns the full delivery machinery — queues, the step
    clock, fault sites, digest verification, counters — and delivers
    losslessly; :class:`ChaosTransport` overrides only the routing
    policy. ``query`` is the ADVISORY side channel (prefix-affinity
    probes, construction-time gauge seeding): best-effort reads that
    never carry stream state, executed directly under loopback and
    refused (``None``) across a partition.
    """

    def __init__(self):
        self._handlers: dict = {}
        self._query_handlers: dict = {}
        self._inboxes: dict[str, list] = {}
        self._ready: list[Message] = []
        self._delayed: list[tuple[int, int, Message]] = []
        self._step = 0
        self._send_seq = 0
        self.counters: dict[str, int] = {
            "sent": 0, "received": 0, "dropped": 0, "duplicated": 0,
            "delayed": 0, "reordered": 0, "held": 0,
            "corrupt_injected": 0, "corrupt_dropped": 0,
            "fenced_dropped": 0,
        }

    # ---- endpoints ----

    def bind(self, name: str, handler=None) -> None:
        """Attach an endpoint: ``handler(msg)`` runs at delivery; with
        no handler the endpoint gets an inbox drained via :meth:`recv`."""
        if handler is not None:
            self._handlers[name] = handler
        else:
            self._inboxes.setdefault(name, [])

    def bind_query(self, name: str, fn) -> None:
        """Attach the advisory query handler ``fn(kind, payload)``."""
        self._query_handlers[name] = fn

    # ---- clock ----

    def tick(self, step: int) -> None:
        """Advance the transport clock (router steps). Delayed messages
        whose release step arrived become deliverable, in msg_id order."""
        self._step = int(step)
        due = [e for e in self._delayed if e[0] <= self._step]
        if due:
            self._delayed = [e for e in self._delayed if e[0] > self._step]
            for _, _, msg in sorted(due, key=lambda e: e[1]):
                self._ready.append(msg)

    # ---- send / deliver ----

    def send(self, msg: Message) -> None:
        """Accept a message for delivery. Fires the
        ``fleet.transport.send`` fault site, then the routing policy
        (:meth:`_route` — lossless here, hostile in the chaos
        subclass). Re-sending the same :class:`Message` retransmits it
        with a fresh ``msg_id`` (fresh chaos draws) but the SAME seq,
        so receiver dedup still collapses it."""
        self._send_seq += 1
        msg.msg_id = self._send_seq
        self.counters["sent"] += 1
        fx = _trip_transport_site("fleet.transport.send", msg, self._step)
        if fx["corrupt"]:
            msg = _corrupt_copy(msg)
            self.counters["corrupt_injected"] += 1
        if fx["drop"]:
            self.counters["dropped"] += 1
            return
        if fx["dup"]:
            self.counters["duplicated"] += 1
            self._route(copy.copy(msg))
        if fx["delay"]:
            self.counters["delayed"] += 1
            self._delayed.append(
                (self._step + int(fx["delay"]), msg.msg_id, msg))
            return
        self._route(msg)

    def _route(self, msg: Message) -> None:
        """Routing policy hook: the lossless base just queues for
        delivery."""
        self._ready.append(msg)

    def _order_batch(self, batch: list) -> list:
        """Delivery order within one pump sweep — FIFO here; the chaos
        transport may shuffle deterministically."""
        return batch

    def pump(self) -> None:
        """Run deliveries until quiescent. Handlers (the engine
        servers) may send replies mid-pump; those deliver in the same
        call, which is what makes loopback exchanges synchronous."""
        guard = 0
        while self._ready:
            batch, self._ready = self._order_batch(self._ready), []
            for msg in batch:
                self._deliver(msg)
            guard += 1
            if guard > 100_000:
                raise RuntimeError("transport pump did not quiesce")

    def _deliver(self, msg: Message) -> None:
        fx = _trip_transport_site("fleet.transport.recv", msg, self._step)
        if fx["drop"]:
            self.counters["dropped"] += 1
            return
        if fx["dup"]:
            # duplicate before corrupting — the copy travels clean, so
            # each corruption damages exactly one delivery
            self.counters["duplicated"] += 1
            self._ready.append(copy.copy(msg))
        if fx["corrupt"]:
            msg = _corrupt_copy(msg)
            self.counters["corrupt_injected"] += 1
        if fx["delay"]:
            self.counters["delayed"] += 1
            self._delayed.append(
                (self._step + int(fx["delay"]), msg.msg_id, msg))
            return
        # receive-side integrity gate: the body digest must match the
        # bytes, and every snapshot must pass its own digest re-verify.
        # A corrupt body drops the whole message; a corrupt snapshot is
        # stripped (the submit degrades to full replay) — wrong bytes
        # are never consumed either way.
        try:
            if not msg.verify():
                raise TransportError(
                    f"payload digest mismatch on {msg.path} "
                    f"({msg.src} -> {msg.dst})")
        except TransportError:
            self.counters["corrupt_dropped"] += 1
            return
        if msg.snaps:
            kept = tuple(s for s in msg.snaps if s.verify())
            if len(kept) != len(msg.snaps):
                self.counters["corrupt_dropped"] += len(msg.snaps) - len(kept)
                msg = copy.copy(msg)
                msg.snaps = kept
        self.counters["received"] += 1
        handler = self._handlers.get(msg.dst)
        if handler is not None:
            try:
                handler(msg)
            except StaleEpochError:
                # a fenced replica refusing zombie-epoch work is the
                # fence WORKING, not a delivery failure
                self.counters["fenced_dropped"] += 1
            return
        self._inboxes.setdefault(msg.dst, []).append(msg)

    def recv(self, dst: str) -> list:
        """Drain an inbox endpoint (the router's receive path)."""
        box = self._inboxes.get(dst)
        if not box:
            return []
        self._inboxes[dst] = []
        return box

    # ---- advisory side channel ----

    def query(self, dst: str, kind: str, payload: dict):
        """Best-effort advisory read against ``dst`` (affinity probes,
        gauge seeding). Loopback executes directly; a chaos transport
        refuses it across a partition. Never used for stream state."""
        fn = self._query_handlers.get(dst)
        if fn is None:
            return None
        return fn(kind, payload)

    # ---- introspection ----

    def stats(self) -> dict:
        return {**self.counters,
                "in_flight": len(self._ready) + len(self._delayed)
                + self._held_count()}

    def _held_count(self) -> int:
        return 0


def _trip_transport_site(site: str, msg: Message, step: int) -> dict:
    """Fire a transport fault site with the drop/dup/delay/corrupt
    action callbacks; returns the effect flags the site armed."""
    fx = {"drop": False, "dup": False, "delay": 0, "corrupt": False}
    if _fault.active_plan() is None:
        return fx
    _fault.trip(
        site, step=step, path=msg.path,
        drop=lambda: fx.__setitem__("drop", True),
        dup=lambda: fx.__setitem__("dup", True),
        delay=lambda steps: fx.__setitem__("delay", max(1, int(steps))),
        corrupt=lambda: fx.__setitem__("corrupt", True))
    return fx


def _corrupt_copy(msg: Message) -> Message:
    """Flip one byte of the wire payload WITHOUT updating any digest —
    the receive-side re-verify must catch it. Prefers the body; a
    message whose payload is its snapshots corrupts the first snapshot
    instead (its own page digests catch that)."""
    out = copy.copy(msg)
    if len(out.body) > 2:
        flat = bytearray(out.body)
        flat[len(flat) // 2] ^= 0xFF
        out.body = bytes(flat)
        out._payload_cache = None
    elif out.snaps:
        out.snaps = tuple(copy.deepcopy(s) for s in out.snaps)
        out.snaps[0].corrupt()
    return out


class LoopbackTransport(Transport):
    """The default in-process wire: synchronous, lossless, ordered —
    bitwise-identical behavior to the pre-transport fleet. It still
    runs the full message path (serialization, digests, fault sites),
    so a FaultPlan can make even loopback lossy for chaos tests."""


class ChaosTransport(Transport):
    """Seeded hostile network. Every per-message decision is a sha256
    draw over ``(seed, decision, msg_id)`` — no wall-clock entropy, so
    a chaos run replays bit-identically.

    - ``drop_p``    — message vanishes
    - ``dup_p``     — message delivers twice (same seq: receiver dedups)
    - ``delay_p``   / ``max_delay_steps`` — delivery postponed 1..N
      router steps on the injectable clock
    - ``corrupt_p`` — one payload byte flips, digests untouched (the
      receive-side re-verify MUST catch it)
    - ``reorder``   — each pump sweep delivers in hash-shuffled order
    - partitions    — :meth:`partition` blocks a direction (or both);
      blocked messages are HELD and released at :meth:`heal` / window
      end, so stale zombie traffic arrives late instead of vanishing —
      the epoch-fencing scenario.
    """

    def __init__(self, seed: int = 0, drop_p: float = 0.0,
                 dup_p: float = 0.0, delay_p: float = 0.0,
                 max_delay_steps: int = 3, corrupt_p: float = 0.0,
                 reorder: bool = False):
        super().__init__()
        self.seed = int(seed)
        self.drop_p = float(drop_p)
        self.dup_p = float(dup_p)
        self.delay_p = float(delay_p)
        self.max_delay_steps = max(1, int(max_delay_steps))
        self.corrupt_p = float(corrupt_p)
        self.reorder = bool(reorder)
        # active windows: dicts with a, b, two_way, start, until
        self._partitions: list[dict] = []
        self._held: list[tuple[int, Message]] = []

    # ---- deterministic draws ----

    def _draw(self, what: str, msg_id: int) -> float:
        h = hashlib.sha256(
            f"chaos:{self.seed}:{what}:{msg_id}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2**64

    # ---- partitions ----

    def partition(self, a: str, b: str, two_way: bool = True,
                  start: int | None = None,
                  until: int | None = None) -> None:
        """Block ``a -> b`` (and ``b -> a`` when ``two_way``) from step
        ``start`` (now if None) until step ``until`` (or until
        :meth:`heal`). Blocked messages are held, not dropped."""
        self._partitions.append({
            "a": a, "b": b, "two_way": bool(two_way),
            "start": self._step if start is None else int(start),
            "until": until if until is None else int(until)})

    def heal(self) -> None:
        """End every partition now and release held traffic."""
        self._partitions.clear()
        self._release_held()

    def _blocked(self, src: str, dst: str) -> bool:
        for w in self._partitions:
            if w["start"] > self._step:
                continue
            if w["until"] is not None and self._step >= w["until"]:
                continue
            if (src, dst) == (w["a"], w["b"]):
                return True
            if w["two_way"] and (src, dst) == (w["b"], w["a"]):
                return True
        return False

    def _release_held(self) -> None:
        if not self._held:
            return
        still, released = [], []
        for mid, msg in self._held:
            if self._blocked(msg.src, msg.dst):
                still.append((mid, msg))
            else:
                released.append((mid, msg))
        self._held = still
        for _, msg in sorted(released, key=lambda e: e[0]):
            self._ready.append(msg)

    def _held_count(self) -> int:
        return len(self._held)

    # ---- routing policy ----

    def tick(self, step: int) -> None:
        super().tick(step)
        # windows that expired this step release their held traffic
        self._partitions = [w for w in self._partitions
                            if w["until"] is None or w["until"] > step]
        self._release_held()

    def _route(self, msg: Message) -> None:
        mid = msg.msg_id
        if self._blocked(msg.src, msg.dst):
            self.counters["held"] += 1
            self._held.append((mid, msg))
            return
        if self._draw("drop", mid) < self.drop_p:
            self.counters["dropped"] += 1
            return
        if self._draw("dup", mid) < self.dup_p:
            # duplicate BEFORE corrupting: the copy is a separate wire
            # journey, so one corruption draw damages one delivery and
            # corrupt_injected == corrupt_dropped stays exact
            self.counters["duplicated"] += 1
            self._ready.append(copy.copy(msg))
        if self._draw("corrupt", mid) < self.corrupt_p:
            msg = _corrupt_copy(msg)
            self.counters["corrupt_injected"] += 1
        if self._draw("delay", mid) < self.delay_p:
            steps = 1 + int(self._draw("delay_steps", mid)
                            * self.max_delay_steps)
            self.counters["delayed"] += 1
            self._delayed.append((self._step + steps, mid, msg))
            return
        self._ready.append(msg)

    def _order_batch(self, batch: list) -> list:
        if not self.reorder or len(batch) < 2:
            return batch
        self.counters["reordered"] += 1
        return sorted(batch,
                      key=lambda m: self._draw("order", m.msg_id))

    def query(self, dst: str, kind: str, payload: dict):
        # advisory reads cross the same partitions the stream does
        if self._blocked("router", dst) or self._blocked(dst, "router"):
            return None
        return super().query(dst, kind, payload)


# ---------------------------------------------------------------------------
# replica-side shim
# ---------------------------------------------------------------------------

class EngineServer:
    """One replica's message endpoint: owns the engine, executes router
    commands exactly once under at-least-once delivery, and streams
    seq-numbered result batches back.

    Dedup keys: submits by ``(rid, epoch, attempt)`` with the reply
    cached and re-sent verbatim (same seq — the router collapses it);
    steps by the router's step seqno (a duplicate STEP never re-steps
    the engine, it only triggers retransmission of unacked results);
    drain by a one-shot latch. A FENCE for epoch ``e`` raises this
    server's floor to ``e+1``: zombie-epoch traffic after that is
    refused with :class:`StaleEpochError` (counted by the transport as
    ``fenced_dropped``) — a fenced replica can never ack stale work.

    Disaggregated serving (SERVING.md "Disaggregated serving") adds
    the KV-handoff half of the protocol: after each STEP/DRAIN the
    server drains the engine's handoff outbox and streams every
    finished-prefill KV export to the router as an epoch-stamped
    ``KV_OFFER`` (a seq-numbered stream kind — at-least-once with
    dedup for free), retaining a copy in ``_handoff_held`` until the
    router's ``KV_COMMIT`` confirms a decode replica landed it. A
    ``KV_PULL`` is executed exactly like a snapshot-seeded SUBMIT (the
    decode replica pulls the offered KV into its pool via
    ``restore_request``/``inject_prefix``) and replies SUBMIT_REPLY
    with a ``kv_injected`` verdict so the router can count payloads
    the digest gate refused."""

    STREAM_KINDS = ("SUBMIT_REPLY", "STEP_RESULTS", "DRAIN_RESULTS",
                    "SNAPSHOT_DATA", "ERROR", "KV_OFFER")

    def __init__(self, idx: int, engine, transport: Transport,
                 router: str = "router", step_mode: str = "immediate"):
        self.idx = int(idx)
        self.engine = engine
        self.transport = transport
        self.name = f"replica:{idx}"
        self._router = router
        self._min_epoch = 0           # FENCE floor: epochs below are refused
        self._epoch_seen = 0          # highest epoch the router spoke at
        self._out_seq = 0
        self._resend: dict[int, Message] = {}   # unacked stream batches
        self._submit_replies: dict = {}         # (rid, epoch, attempt) -> msg
        self._last_step_seq = -1
        self._drain_reply: Message | None = None
        # "deferred" decouples engine stepping from message handling: a
        # STEP only LATCHES (multi-host replica hosts run the engine
        # between transport pumps, so a burst of retransmitted STEPs
        # can never wedge the handler in back-to-back engine steps and
        # starve heartbeat acks into a lease expiry). "immediate" —
        # the in-process default — steps inside the handler, which is
        # what every loopback/chaos suite pins.
        if step_mode not in ("immediate", "deferred"):
            raise ValueError(f"unknown step_mode {step_mode!r}")
        self.step_mode = step_mode
        self._step_pending: int | None = None   # latched epoch, if any
        # disaggregated serving: offered-but-uncommitted KV exports,
        # freed by KV_COMMIT (or re-offerable if the router asks again)
        self._handoff_held: dict[str, object] = {}
        transport.bind(self.name, self.handle)
        transport.bind_query(self.name, self.query)

    # ---- gauges: the health payload piggybacked on every reply ----

    def gauges(self) -> dict:
        eng = self.engine
        sched = eng.scheduler
        pool = getattr(eng, "pool", None)
        cap = getattr(eng, "_token_capacity_per_step", None)
        mqd = getattr(sched, "max_queue_depth", None)
        return {
            "queue_depth": int(sched.queue_depth),
            "running": len(sched.running),
            "pool_utilization": (float(pool.utilization())
                                 if pool is not None else 0.0),
            "draining": bool(getattr(eng, "_draining", False)),
            "brownout_level": int(getattr(eng, "brownout_level", 0)),
            "tp_degree": int(getattr(eng, "tp", 1)),
            "pp_degree": int(getattr(eng, "pp", 1)),
            "max_queue_depth": None if mqd is None else int(mqd),
            "token_capacity": None if cap is None else int(cap()),
            "handoff_held": len(self._handoff_held),
            "pid": os.getpid(),
        }

    def query(self, kind: str, payload: dict):
        """Advisory reads: prefix-affinity probes and gauge seeding."""
        if kind == "affinity":
            pool = getattr(self.engine, "pool", None)
            if pool is None or not getattr(pool, "cache_enabled", False):
                return {"cached_tokens": 0}
            try:
                # multi-tenant LoRA: the probe matches under the
                # adapter's prefix-cache namespace (a foreign adapter's
                # identical prompt is not a hit), and a replica whose
                # AdapterPool already holds the adapter resident earns
                # one page worth of cached tokens on top — skipping the
                # weight stream-in beats a few cached prompt tokens
                ns = (bytes.fromhex(payload["adapter"])
                      if payload.get("adapter") else b"")
                hit = pool.match_prefix(payload["prompt"], namespace=ns)
                cached = int(hit.cached_tokens)
                adapters = getattr(self.engine, "adapters", None)
                if ns and adapters is not None and adapters.resident(ns):
                    cached += int(getattr(pool, "page_size", 0))
                return {"cached_tokens": cached}
            except Exception:  # noqa: BLE001 — affinity is best-effort
                return {"cached_tokens": 0}
        if kind == "gauges":
            return self.gauges()
        if kind == "introspect":
            # multi-host test/debug surface: determinism evidence a
            # cross-process caller cannot read off the engine object
            counts = getattr(self.engine, "step_program_counts", None)
            audit = getattr(self.engine, "audit_pool", None)
            out = {"pid": os.getpid(),
                   "step_program_counts":
                       dict(counts()) if counts is not None else {}}
            try:
                if audit is not None:
                    audit()
                out["audit_ok"] = True
            except Exception as e:  # noqa: BLE001 — carry the evidence
                out["audit_ok"] = False
                out["audit_error"] = str(e)
            return out
        if kind == "admission_check":
            check = getattr(self.engine, "admission_check", None)
            if check is None:
                return {"ok": True}
            try:
                check(payload["prompt_len"], payload["max_new_tokens"])
            except RequestTooLargeError as e:
                return {"ok": False, "detail": str(e)}
            return {"ok": True}
        return None

    # ---- the message handler ----

    def handle(self, msg: Message) -> None:
        if msg.epoch < self._min_epoch:
            raise StaleEpochError(
                f"replica {self.idx} fenced at epoch {self._min_epoch}; "
                f"refusing {msg.kind} from epoch {msg.epoch}")
        self._epoch_seen = max(self._epoch_seen, msg.epoch)
        p = msg.payload()
        ack = p.get("ack")
        if ack is not None:
            for seq in [s for s in self._resend if s <= ack]:
                del self._resend[seq]
        kind = msg.kind
        if kind == "FENCE":
            self._min_epoch = max(self._min_epoch, msg.epoch + 1)
            return
        # any contact from the router retransmits whatever it has not
        # acked yet — the at-least-once half of exactly-once
        self._resend_unacked()
        if kind == "HEARTBEAT":
            self.transport.send(Message.make(
                "HEARTBEAT_ACK", self.name, self._router, epoch=msg.epoch,
                payload={"hb_seq": p["hb_seq"], "sent_step": p["sent_step"],
                         "gauges": self.gauges()}))
        elif kind in ("SUBMIT", "KV_PULL"):
            # a KV_PULL is a submit seeded with the offered handoff KV
            # — same dedup key, same cached-reply retransmission
            self._handle_submit(msg, p)
        elif kind == "STEP":
            self._handle_step(msg, p)
        elif kind == "DRAIN":
            self._handle_drain(msg, p)
        elif kind == "SNAPSHOT_FETCH":
            self._handle_snapshot_fetch(msg, p)
        elif kind == "KV_COMMIT":
            # a decode replica landed the handoff — release the held
            # copy (idempotent under redelivery)
            self._handoff_held.pop(p.get("rid", msg.rid), None)

    def _resend_unacked(self) -> None:
        for seq in sorted(self._resend):
            self.transport.send(self._resend[seq])

    def _stream(self, kind: str, epoch: int, rid: str, payload: dict,
                snaps: tuple = ()) -> Message:
        self._out_seq += 1
        m = Message.make(kind, self.name, self._router, epoch=epoch,
                         seq=self._out_seq, rid=rid, payload=payload,
                         snaps=snaps)
        self._resend[self._out_seq] = m
        self.transport.send(m)
        return m

    # ---- command execution (each idempotent under redelivery) ----

    def _handle_submit(self, msg: Message, p: dict) -> None:
        key = (msg.rid, msg.epoch, p["attempt"])
        cached = self._submit_replies.get(key)
        if cached is not None:
            self.transport.send(cached)   # same seq: the router dedups
            return
        eng = self.engine
        snap = msg.snaps[0] if msg.snaps else None
        if getattr(eng, "restore_request", None) is None:
            snap = None
        tenant, priority = int(p.get("tenant", 0)), int(p.get("priority", 0))
        tp_kw = ({"tenant": tenant, "priority": priority}
                 if (tenant, priority) != (0, 0) else {})
        reply = {"rid": msg.rid, "attempt": p["attempt"], "ok": True,
                 "used_snapshot": False, "restored": 0,
                 "kv_injected": snap is not None}

        def _restore_misses() -> int:
            c = getattr(getattr(eng, "metrics", None), "counters", None)
            if c is None:
                return 0
            return (c.get("snapshot_restore_failed", 0)
                    + c.get("snapshot_restore_corrupt", 0))

        if p.get("prefill_only"):
            tp_kw["prefill_only"] = True
        if p.get("adapter"):
            tp_kw["adapter"] = p["adapter"]
        try:
            if snap is not None:
                misses0 = _restore_misses()
                tp_kw.pop("prefill_only", None)   # a seeded submit
                tp_kw.pop("adapter", None)   # the snapshot itself is
                # adapter-bound; restore_request re-resolves it
                # already owns its KV — nothing left to hand off
                eng.restore_request(snap, **tp_kw)
                reply["used_snapshot"] = True
                reply["restored"] = len(snap.tokens)
                # the digest gate (snap.verify inside restore_request)
                # decides whether the pages actually injected; a refusal
                # falls back to a full recompute on THIS replica — count
                # it for the router's handoff_corrupt ledger
                reply["kv_injected"] = _restore_misses() == misses0
            else:
                eng.add_request(
                    p["prompt"], p["max_new_tokens"],
                    sampling=SamplingParams(**p["sampling"]),
                    eos_token_id=p["eos_token_id"], rid=msg.rid,
                    deadline_s=p["deadline_s"],
                    max_queue_wait_s=p["max_queue_wait_s"], **tp_kw)
        except RequestTooLargeError as e:
            reply.update(ok=False, error="RequestTooLargeError",
                         retryable=False, detail=str(e))
        except _fault.FaultInjected as e:
            reply.update(ok=False, error="FaultInjected",
                         retryable=True, detail=str(e))
        except ServingError as e:
            reply.update(ok=False, error=type(e).__name__,
                         retryable=bool(e.retryable), detail=str(e))
        reply["gauges"] = self.gauges()
        self._submit_replies[key] = self._stream(
            "SUBMIT_REPLY", msg.epoch, msg.rid, reply)

    def _handle_step(self, msg: Message, p: dict) -> None:
        if p["router_step"] <= self._last_step_seq:
            return                       # duplicate STEP: never re-step
        self._last_step_seq = int(p["router_step"])
        if self.step_mode == "deferred":
            self._step_pending = msg.epoch
            return
        self._do_step(msg.epoch)

    def pending_step(self) -> bool:
        """True when a latched (deferred-mode) STEP awaits execution."""
        return self._step_pending is not None

    def run_pending_step(self) -> None:
        """Execute the latched STEP (deferred mode). Duplicate STEPs
        between pumps collapse into one engine step — the same dedup
        the step seqno gives immediate mode."""
        if self._step_pending is None:
            return
        epoch, self._step_pending = self._step_pending, None
        self._do_step(epoch)

    def _do_step(self, epoch: int) -> None:
        eng = self.engine
        if not eng.scheduler.has_work():
            self._stream("STEP_RESULTS", epoch, "",
                         {"events": [], "gauges": self.gauges()})
            return
        try:
            events = eng.step()
        except SchedulerStalledError as e:
            self._stream("ERROR", epoch, "",
                         {"reason": "stalled",
                          "error": "SchedulerStalledError",
                          "snapshot": e.snapshot,
                          "gauges": self.gauges()})
            return
        except _fault.FaultInjected:
            self._stream("ERROR", epoch, "",
                         {"reason": "killed", "error": "FaultInjected",
                          "gauges": self.gauges()})
            return
        except ServingError as e:
            self._stream("ERROR", epoch, "",
                         {"reason": f"error:{type(e).__name__}",
                          "error": type(e).__name__,
                          "gauges": self.gauges()})
            return
        self._stream("STEP_RESULTS", epoch, "",
                     {"events": events, "gauges": self.gauges()})
        self._stream_handoffs(epoch)

    def _stream_handoffs(self, epoch: int) -> None:
        """Publish every finished-prefill KV export the engine produced
        this step as a ``KV_OFFER`` stream message (the sealed snapshot
        rides ``msg.snaps``, so the wire's digest gate covers the
        payload page by page). Offers are emitted AFTER the step's
        results: the router sees the request's "handoff" finish first,
        then the offer — though its offer handler accepts either
        order."""
        take = getattr(self.engine, "take_handoffs", None)
        if take is None:
            return
        for snap in take():
            self._handoff_held[snap.rid] = snap
            self._stream("KV_OFFER", epoch, snap.rid,
                         {"context_len": int(snap.context_len),
                          "nbytes": int(snap.nbytes),
                          "gauges": self.gauges()},
                         snaps=(snap,))

    def _handle_drain(self, msg: Message, p: dict) -> None:
        if self._drain_reply is not None:
            self.transport.send(self._drain_reply)
            return
        try:
            self.engine.drain(timeout_s=p.get("timeout_s"))
        except (ServingError, _fault.FaultInjected):
            self._drain_reply = self._stream(
                "ERROR", msg.epoch, "",
                {"reason": "died_in_drain", "error": "drain",
                 "gauges": self.gauges()})
            return
        self._drain_reply = self._stream(
            "DRAIN_RESULTS", msg.epoch, "",
            {"events": self.engine.last_drain_events,
             "gauges": self.gauges()})
        self._stream_handoffs(msg.epoch)

    def announce_drain(self, timeout_s: float | None = None) -> None:
        """Replica-INITIATED drain: a multi-host replica host's SIGTERM
        path (the preemption guard tripped). Runs the engine drain and
        streams an unsolicited ``DRAIN_RESULTS`` at the highest epoch
        the router has spoken at — the router's apply path translates
        drain events regardless of who asked, so in-flight requests
        finish or classify as preempted instead of dying with the
        process. One-shot via the same latch as a router-driven DRAIN."""
        if self._drain_reply is not None:
            self.transport.send(self._drain_reply)
            return
        epoch = max(self._epoch_seen, self._min_epoch)
        try:
            self.engine.drain(timeout_s=timeout_s)
        except (ServingError, _fault.FaultInjected):
            self._drain_reply = self._stream(
                "ERROR", epoch, "",
                {"reason": "died_in_drain", "error": "drain",
                 "gauges": self.gauges()})
            return
        self._drain_reply = self._stream(
            "DRAIN_RESULTS", epoch, "",
            {"events": self.engine.last_drain_events,
             "gauges": self.gauges()})

    def _handle_snapshot_fetch(self, msg: Message, p: dict) -> None:
        store = getattr(self.engine, "snapshot_store", None)
        snaps = []
        if store is not None:
            known = p.get("known", {})
            for rid in store.rids():
                snap = store.get(rid)     # digest re-verified by the store
                if snap is None:
                    continue
                if len(snap.tokens) <= int(known.get(rid, -1)):
                    continue              # the router already has this much
                snaps.append(snap)
        self._stream("SNAPSHOT_DATA", msg.epoch, "",
                     {"rids": [s.rid for s in snaps],
                      "gauges": self.gauges()},
                     snaps=tuple(snaps))
