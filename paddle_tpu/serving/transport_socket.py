"""Multi-host fleet transport: the PR-15 wire over real TCP sockets
(SERVING.md "Multi-host serving"; the multi-host half of ROADMAP item 4).

:class:`SocketTransport` carries the existing canonical
:class:`~.transport.Message` bytes between OS processes with
length-prefixed framing. It deliberately adds NO protocol: every
guarantee — digest-gated receive, epoch fencing, seq-ordered
exactly-once streams, lease-based membership, snapshot-seeded bounded
replay — lives in the transport-agnostic layer above
(serving/transport.py + fleet.py), and this module only has to move
bytes and lose them honestly. Delivery into the process reuses the
base class's ``_deliver`` verbatim, so the ``fleet.transport.send`` /
``fleet.transport.recv`` fault sites, the blake2b body digest gate and
the snapshot strip-on-corruption path behave bit-identically to
loopback.

Topology: one endpoint LISTENS (the router, ``listen=(host, 0)``),
the others CONNECT (replica hosts, ``connect={"router": addr}``) and
introduce themselves with a HELLO frame carrying their endpoint name —
so the router never needs to know replica addresses, only replicas
need the router's. Reconnects reuse :func:`~.transport.deterministic_jitter`
for backoff phasing (exponential, capped, keyed on the endpoint pair
and attempt count — chaos runs replay the same schedule).

Frame format (all integers big-endian)::

    +----+----+------+------------------+
    | PT | ty | len  | payload[len]     |    ty: 1=MESSAGE 2=HELLO
    +----+----+------+------------------+        3=PING 4=PONG
      2B   1B   4B                               5=QUERY 6=QREPLY

A MESSAGE payload is ``u32 header_len | header_json | body |
snapshot_blob``: the header carries routing metadata plus the body
digest and per-snapshot array specs VERBATIM (hex) — digests are never
recomputed in transit, so a flipped byte anywhere in body or snapshot
bytes fails the existing receive-side re-verify
(``corrupt_dropped`` / snapshot stripped), exactly like loopback
corruption.

Failure accounting (all in ``counters`` and exported as
``paddle_serving_fleet_transport_socket_*``):

- ``socket_torn_frames``   — a connection died mid-frame (short read);
  the partial bytes are discarded, the stream layer retransmits.
- ``socket_resets``        — connection reset / abort observed.
- ``socket_half_open``     — a peer went silent past ``half_open_s``
  while owing a PONG: the classic half-open TCP state, detected by the
  application-level ping and resolved by tearing the connection down.
- ``socket_backpressure_stalls`` — a per-peer bounded outbound queue
  hit its limit; the overflowing frame is dropped (counted ``dropped``)
  rather than buffering unboundedly — the stream layer's at-least-once
  resend makes the drop protocol-safe.
- ``socket_protocol_errors`` — bad magic / oversized length /
  undecodable MESSAGE: the connection is reset (never "resynced").
- ``socket_reconnects`` / ``socket_accepts`` / frame+byte counters.

Connection-level chaos: :class:`FrameChaos` is a seeded fault shim at
the FRAME layer (below everything the ChaosTransport suite models) —
per-frame sha256 draws inject byte corruption inside the message body
region (the digest gate must catch it: ``corrupt_injected`` ==
receiver ``corrupt_dropped``), link stalls, and mid-frame RST resets
(the receiver sees a torn frame + reset). Same seed, same weather.

Fault sites (RESILIENCE.md "Multi-host playbook"):
``fleet.transport.connect`` fires per dial attempt (``path`` = peer
name; ``drop`` skips the attempt into backoff, ``delay`` pushes it
``arg`` seconds, ``raise`` models a refused/reset connect) and
``fleet.transport.accept`` per accepted connection (``path`` =
``ip:port``; ``drop`` closes it silently, ``delay`` parks it ``arg``
seconds, ``raise`` closes it with an RST). Both replay from
``PADDLE_FAULT_PLAN``, which spawned replica hosts inherit.
"""

from __future__ import annotations

import hashlib
import json
import select
import socket as _socket
import struct
import time
from collections import deque
from dataclasses import dataclass

from ..distributed import fault as _fault
from .errors import ReplicaSpawnError, TransportError
from .metrics import percentile
from .snapshot import snapshot_from_wire, snapshot_to_wire
from .transport import Message, Transport, deterministic_jitter

__all__ = ["SocketTransport", "FrameDecoder", "FrameChaos",
           "FrameProtocolError", "encode_message", "decode_message",
           "FT_MESSAGE", "FT_HELLO", "FT_PING", "FT_PONG",
           "FT_QUERY", "FT_QREPLY"]

_MAGIC = b"PT"
_HEADER = struct.Struct(">2sBI")
_U32 = struct.Struct(">I")
_MAX_FRAME = 1 << 30          # 1 GiB: far above any snapshot batch

FT_MESSAGE = 1
FT_HELLO = 2
FT_PING = 3
FT_PONG = 4
FT_QUERY = 5
FT_QREPLY = 6
_FRAME_TYPES = frozenset((FT_MESSAGE, FT_HELLO, FT_PING, FT_PONG,
                          FT_QUERY, FT_QREPLY))


class FrameProtocolError(TransportError):
    """The byte stream is not a valid frame sequence (bad magic, unknown
    frame type, or an absurd length prefix). There is no safe way to
    resynchronize a corrupted length-prefixed stream — the connection
    is reset and the stream layer retransmits."""


class FrameDecoder:
    """Incremental length-prefixed frame parser: feed arbitrary byte
    chunks, get complete ``(frame_type, payload)`` frames out. Torn
    frames (a connection dying mid-frame) simply stay in ``pending``
    for the caller to count and discard."""

    def __init__(self):
        self._buf = bytearray()

    @property
    def pending(self) -> int:
        """Bytes buffered that do not yet form a complete frame —
        nonzero at disconnect means the peer died mid-frame."""
        return len(self._buf)

    def feed(self, data: bytes) -> list:
        self._buf.extend(data)
        frames = []
        while True:
            if len(self._buf) < _HEADER.size:
                return frames
            magic, ftype, length = _HEADER.unpack_from(self._buf)
            if magic != _MAGIC or ftype not in _FRAME_TYPES:
                raise FrameProtocolError(
                    f"bad frame header: magic={magic!r} type={ftype}")
            if length > _MAX_FRAME:
                raise FrameProtocolError(
                    f"frame length {length} exceeds limit {_MAX_FRAME}")
            if len(self._buf) < _HEADER.size + length:
                return frames
            payload = bytes(self._buf[_HEADER.size:_HEADER.size + length])
            del self._buf[:_HEADER.size + length]
            frames.append((ftype, payload))


def _frame(ftype: int, payload: bytes) -> bytes:
    return _HEADER.pack(_MAGIC, ftype, len(payload)) + payload


# ---------------------------------------------------------------------------
# Message <-> wire bytes
# ---------------------------------------------------------------------------


def encode_message(msg: Message) -> bytes:
    """Serialize one :class:`Message` to MESSAGE-frame payload bytes.
    The body bytes and every digest travel verbatim — the receive side
    re-verifies against exactly what the sender sealed."""
    snap_meta, blobs = [], []
    for s in msg.snaps:
        meta, blob = snapshot_to_wire(s)
        snap_meta.append(meta)
        blobs.append(blob)
    header = {"kind": msg.kind, "src": msg.src, "dst": msg.dst,
              "epoch": msg.epoch, "seq": msg.seq, "rid": msg.rid,
              "digest": msg.digest.hex(),
              "body_nbytes": len(msg.body),
              "snap_nbytes": [len(b) for b in blobs],
              "snaps": snap_meta}
    hj = json.dumps(header, separators=(",", ":")).encode()
    return _U32.pack(len(hj)) + hj + msg.body + b"".join(blobs)


def decode_message(payload: bytes) -> Message:
    """Rebuild a :class:`Message` from MESSAGE-frame payload bytes —
    as received, damage included: the transport's ``_deliver`` digest
    gate (not this function) decides whether the bytes are usable."""
    if len(payload) < _U32.size:
        raise FrameProtocolError("message frame shorter than its header")
    (hlen,) = _U32.unpack_from(payload)
    if _U32.size + hlen > len(payload):
        raise FrameProtocolError("message header overruns the frame")
    try:
        header = json.loads(payload[_U32.size:_U32.size + hlen].decode())
        off = _U32.size + hlen
        body = payload[off:off + int(header["body_nbytes"])]
        off += int(header["body_nbytes"])
        snaps = []
        for meta, n in zip(header["snaps"], header["snap_nbytes"]):
            snaps.append(snapshot_from_wire(meta, payload[off:off + int(n)]))
            off += int(n)
        return Message(kind=header["kind"], src=header["src"],
                       dst=header["dst"], epoch=int(header["epoch"]),
                       seq=int(header["seq"]), rid=header["rid"],
                       body=body, digest=bytes.fromhex(header["digest"]),
                       snaps=tuple(snaps))
    except FrameProtocolError:
        raise
    except Exception as e:  # noqa: BLE001 — any malformed field
        raise FrameProtocolError(f"undecodable message frame: {e}") from e


# ---------------------------------------------------------------------------
# frame-layer chaos
# ---------------------------------------------------------------------------


@dataclass
class FrameChaos:
    """Seeded connection-level fault shim, applied per outbound MESSAGE
    frame (sha256 draws over ``(seed, decision, frame_seq)`` — same
    seed, same weather, no wall-clock entropy in the DECISIONS; the
    stall duration is wall time because sockets are):

    - ``corrupt_p`` — flip one byte inside the message BODY region
      (frame header and message header stay intact, so the frame
      decodes and the existing digest gate must catch it:
      sender ``corrupt_injected`` == receiver ``corrupt_dropped``).
    - ``reset_p``   — transmit only half the frame, then close with an
      RST: the receiver counts a torn frame and a reset.
    - ``stall_p``   — freeze the link ``stall_s`` seconds (outbound
      frames queue; the peer may ping into half-open detection).
    """

    seed: int = 0
    corrupt_p: float = 0.0
    reset_p: float = 0.0
    stall_p: float = 0.0
    stall_s: float = 0.02

    def _draw(self, what: str, n: int) -> float:
        h = hashlib.sha256(
            f"framechaos:{self.seed}:{what}:{n}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2**64

    def corrupt(self, n: int) -> bool:
        return self._draw("corrupt", n) < self.corrupt_p

    def reset(self, n: int) -> bool:
        return self._draw("reset", n) < self.reset_p

    def stall(self, n: int) -> bool:
        return self._draw("stall", n) < self.stall_p


def _corrupt_frame_payload(payload: bytes) -> bytes:
    """Flip the first body byte of a MESSAGE-frame payload, leaving the
    message header intact — so the frame still parses and the damage is
    the digest gate's to catch (never a protocol error)."""
    (hlen,) = _U32.unpack_from(payload)
    pos = _U32.size + hlen
    if pos >= len(payload):
        return payload
    flat = bytearray(payload)
    flat[pos] ^= 0xFF
    return bytes(flat)


# ---------------------------------------------------------------------------
# the transport
# ---------------------------------------------------------------------------


class _Peer:
    """One live TCP connection. ``name`` is None until its HELLO
    arrives (accepted connections introduce themselves)."""

    __slots__ = ("name", "sock", "decoder", "addr", "last_recv",
                 "last_ping", "pings", "stall_until", "wbuf",
                 "reset_after_wbuf")

    def __init__(self, sock, addr):
        self.name = None
        self.sock = sock
        self.decoder = FrameDecoder()
        self.addr = addr                   # "ip:port" of the far end
        self.last_recv = time.monotonic()
        self.last_ping = 0.0
        self.pings: dict[int, float] = {}  # token -> sent monotonic
        self.stall_until = 0.0
        self.wbuf = b""                    # bytes committed to this socket
        self.reset_after_wbuf = False      # FrameChaos reset armed


class SocketTransport(Transport):
    """The PR-15 message fabric over TCP. See the module docstring for
    the wire format and failure accounting; the behavioural contract is
    the base :class:`~.transport.Transport`'s — ``send``/``pump``/
    ``recv``/``query``/``tick`` — plus connection management:

    - ``node``     — this endpoint's name (``"router"``/``"replica:i"``).
      Locally-bound endpoints still deliver in-process (a router and an
      in-process EngineServer on the SAME SocketTransport short-circuit
      exactly like loopback); only foreign destinations hit the wire.
    - ``listen``   — ``(host, port)`` to accept peers on (port 0 = ephemeral;
      see ``listen_addr``).
    - ``connect``  — ``{peer_name: (host, port)}`` to dial, with
      automatic reconnect (exponential backoff + the shared
      deterministic jitter) for as long as the transport lives.
    - ``chaos``    — an optional :class:`FrameChaos`.

    ``pump()`` is non-blocking while traffic flows; when fully idle it
    blocks in one ``select`` for at most ``poll_s`` — which is what
    paces a quiet router/replica loop without spinning a core.
    """

    def __init__(self, node: str, listen=None, connect=None, *,
                 poll_s: float = 0.005, outbound_limit: int = 512,
                 ping_interval_s: float = 0.25, half_open_s: float = 2.0,
                 query_timeout_s: float = 0.25,
                 reconnect_base_s: float = 0.05,
                 reconnect_max_s: float = 2.0,
                 chaos: FrameChaos | None = None):
        super().__init__()
        self.node = str(node)
        self.poll_s = float(poll_s)
        self.outbound_limit = max(1, int(outbound_limit))
        self.ping_interval_s = float(ping_interval_s)
        self.half_open_s = float(half_open_s)
        self.query_timeout_s = float(query_timeout_s)
        self.reconnect_base_s = float(reconnect_base_s)
        self.reconnect_max_s = float(reconnect_max_s)
        self.chaos = chaos
        self.counters.update({
            "socket_frames_sent": 0, "socket_frames_recv": 0,
            "socket_bytes_sent": 0, "socket_bytes_recv": 0,
            "socket_accepts": 0, "socket_reconnects": 0,
            "socket_resets": 0, "socket_torn_frames": 0,
            "socket_half_open": 0, "socket_backpressure_stalls": 0,
            "socket_protocol_errors": 0,
        })
        self._peers: dict[str, _Peer] = {}       # named, live
        self._anon: list[_Peer] = []             # accepted, pre-HELLO
        self._out: dict[str, deque] = {}         # name -> (fseq, ty, bytes)
        self._dial: dict[str, dict] = {}
        self._pending_accepts: list[tuple] = []  # (release_t, sock, addr)
        self._qreplies: dict[int, object] = {}
        self._qid = 0
        self._ping_seq = 0
        self._frame_seq = 0
        self._rtt: dict[str, list[float]] = {}
        self._closed = False
        self._listener = None
        if listen is not None:
            self._listener = _socket.socket(_socket.AF_INET,
                                            _socket.SOCK_STREAM)
            self._listener.setsockopt(_socket.SOL_SOCKET,
                                      _socket.SO_REUSEADDR, 1)
            self._listener.bind(tuple(listen))
            self._listener.listen(64)
            self._listener.setblocking(False)
        for name, addr in (connect or {}).items():
            self._dial[str(name)] = {"addr": tuple(addr), "attempts": 0,
                                     "next": 0.0, "connected_once": False}

    # ---- addressing ----

    @property
    def listen_addr(self):
        """``(host, port)`` actually bound (port resolved if 0)."""
        if self._listener is None:
            return None
        return self._listener.getsockname()[:2]

    def peer_addr(self, name: str):
        """The far end's ``"ip:port"`` for a connected peer, else None."""
        peer = self._peers.get(name)
        return peer.addr if peer is not None else None

    def peers(self) -> list:
        return sorted(self._peers)

    def wait_peers(self, names, timeout_s: float = 30.0,
                   procs=None) -> None:
        """Block until every named peer has connected and said HELLO —
        the spawn/attach barrier. ``procs`` (optional Popen-likes) lets
        a dead child fail fast with its exit status instead of burning
        the whole timeout. Raises :class:`ReplicaSpawnError`."""
        deadline = time.monotonic() + float(timeout_s)
        missing = [n for n in names if n not in self._peers]
        while missing:
            for p in procs or ():
                rc = p.poll() if hasattr(p, "poll") else None
                if rc is not None:
                    raise ReplicaSpawnError(
                        f"replica process pid={getattr(p, 'pid', '?')} "
                        f"exited rc={rc} before connecting")
            if time.monotonic() >= deadline:
                raise ReplicaSpawnError(
                    f"peers {missing} did not connect within "
                    f"{timeout_s}s (connected: {sorted(self._peers)})")
            self._io_sweep(block_s=min(0.05, self.poll_s or 0.05))
            missing = [n for n in names if n not in self._peers]

    def pending_output(self) -> int:
        """Frames queued or partially written — a drain barrier for a
        replica host flushing its last results before exit."""
        n = sum(len(q) for q in self._out.values())
        n += sum(1 for p in self._peers.values() if p.wbuf)
        return n

    # ---- routing: local short-circuit, else frame to the peer ----

    def _route(self, msg: Message) -> None:
        if msg.dst in self._handlers or msg.dst in self._inboxes:
            self._ready.append(msg)
            return
        self._enqueue(msg.dst, FT_MESSAGE, encode_message(msg))

    def _enqueue(self, name: str, ftype: int, payload: bytes) -> bool:
        if name not in self._peers and name not in self._dial:
            # no connection and nobody dialing one: honest loss (a FENCE
            # to a SIGKILLed replica lands here) — the layer above
            # already treats sends as best-effort
            self.counters["dropped"] += 1
            return False
        q = self._out.setdefault(name, deque())
        if len(q) >= self.outbound_limit:
            self.counters["socket_backpressure_stalls"] += 1
            self._flush_peer(name)                 # try to relieve first
            if len(q) >= self.outbound_limit:
                self.counters["dropped"] += 1      # bounded, never OOM
                return False
        self._frame_seq += 1
        q.append((self._frame_seq, ftype, payload))
        return True

    # ---- pump ----

    def pump(self) -> None:
        if self._closed:
            super().pump()
            return
        self._io_sweep()
        had_work = bool(self._ready)
        super().pump()            # digest gate + handlers, as loopback
        self._io_sweep()          # flush replies the handlers produced
        if not had_work and not self._ready and self.poll_s > 0:
            self._io_sweep(block_s=self.poll_s)
            super().pump()
            self._io_sweep()

    # ---- queries: frame round-trip with a bounded wait ----

    def query(self, dst: str, kind: str, payload: dict):
        if dst in self._query_handlers:           # local endpoint
            return self._query_handlers[dst](kind, payload)
        if dst not in self._peers:
            return None
        self._qid += 1
        qid = self._qid
        body = json.dumps({"qid": qid, "dst": dst, "kind": kind,
                           "payload": payload},
                          separators=(",", ":")).encode()
        if not self._enqueue(dst, FT_QUERY, body):
            return None
        deadline = time.monotonic() + self.query_timeout_s
        while time.monotonic() < deadline:
            self._io_sweep(block_s=0.002)
            if qid in self._qreplies:
                return self._qreplies.pop(qid)
            if dst not in self._peers:            # peer died mid-query
                return None
        return None                               # advisory: degrade

    # ---- the io sweep ----

    def _io_sweep(self, block_s: float = 0.0) -> None:
        if self._closed:
            return
        now = time.monotonic()
        self._service_dials(now)
        self._service_accepts(now)
        if block_s > 0 and not self._ready:
            self._select_wait(block_s)
        self._accept_new()
        for peer in list(self._peers.values()) + list(self._anon):
            self._read_peer(peer)
        self._ping_sweep(time.monotonic())
        for name in set(self._out) | set(self._peers):
            self._flush_peer(name)

    def _select_wait(self, timeout: float) -> None:
        rlist = [p.sock for p in self._peers.values() if p.sock]
        rlist += [p.sock for p in self._anon if p.sock]
        if self._listener is not None:
            rlist.append(self._listener)
        wlist = [p.sock for n, p in self._peers.items()
                 if p.sock and (p.wbuf or self._out.get(n))]
        # a pending dial or parked accept caps how long we may sleep
        wake = [d["next"] for n, d in self._dial.items()
                if n not in self._peers]
        wake += [t for t, _, _ in self._pending_accepts]
        now = time.monotonic()
        if wake:
            timeout = max(0.0, min(timeout, min(wake) - now))
        if not rlist and not wlist:
            time.sleep(min(timeout, 0.05))
            return
        try:
            select.select(rlist, wlist, [], timeout)
        except (OSError, ValueError):
            pass                        # a socket died mid-select; the
            # per-peer read path classifies it next sweep

    # ---- dialing / accepting ----

    def _service_dials(self, now: float) -> None:
        for name, d in self._dial.items():
            if name in self._peers or now < d["next"] or self._closed:
                continue
            fx = {"drop": False, "delay": 0.0}
            if _fault.active_plan() is not None:
                try:
                    _fault.trip(
                        "fleet.transport.connect", step=self._step,
                        path=name,
                        drop=lambda: fx.__setitem__("drop", True),
                        delay=lambda s: fx.__setitem__("delay",
                                                       float(s)))
                except _fault.FaultInjected:
                    # "reset": the far end refused/reset the attempt
                    self.counters["socket_resets"] += 1
                    self._dial_backoff(name, d, now)
                    continue
            if fx["drop"]:
                self._dial_backoff(name, d, now)
                continue
            if fx["delay"]:
                d["next"] = now + fx["delay"]
                continue
            try:
                sock = _socket.create_connection(d["addr"], timeout=0.25)
            except OSError:
                self._dial_backoff(name, d, now)
                continue
            sock.setblocking(False)
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            peer = _Peer(sock, "%s:%d" % sock.getpeername()[:2])
            peer.name = name
            if d["connected_once"]:
                self.counters["socket_reconnects"] += 1
            d["connected_once"] = True
            d["attempts"] = 0
            old = self._peers.get(name)
            if old is not None:
                self._close_sock(old.sock)
            self._peers[name] = peer
            # HELLO must be the first bytes on this socket: commit it to
            # the socket's write buffer ahead of any queued frames
            peer.wbuf = _frame(FT_HELLO, self.node.encode())

    def _dial_backoff(self, name: str, d: dict, now: float) -> None:
        d["attempts"] += 1
        base = self.reconnect_base_s * (2 ** min(d["attempts"] - 1, 6))
        bounded = min(base, self.reconnect_max_s)
        jit = deterministic_jitter(
            f"socket-reconnect:{self.node}:{name}:{d['attempts']}",
            1000) / 1000.0
        d["next"] = now + bounded * (0.5 + 0.5 * jit)

    def _accept_new(self) -> None:
        if self._listener is None:
            return
        while True:
            try:
                conn, addr = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            fx = {"drop": False, "delay": 0.0}
            path = "%s:%d" % addr[:2]
            if _fault.active_plan() is not None:
                try:
                    _fault.trip(
                        "fleet.transport.accept", step=self._step,
                        path=path,
                        drop=lambda: fx.__setitem__("drop", True),
                        delay=lambda s: fx.__setitem__("delay",
                                                       float(s)))
                except _fault.FaultInjected:
                    self.counters["socket_resets"] += 1
                    self._rst_close(conn)
                    continue
            if fx["drop"]:
                conn.close()              # silent: connector sees EOF
                continue
            if fx["delay"]:
                self._pending_accepts.append(
                    (time.monotonic() + fx["delay"], conn, addr))
                continue
            self._adopt(conn, addr)

    def _service_accepts(self, now: float) -> None:
        due = [e for e in self._pending_accepts if e[0] <= now]
        if due:
            self._pending_accepts = [e for e in self._pending_accepts
                                     if e[0] > now]
            for _, conn, addr in due:
                self._adopt(conn, addr)

    def _adopt(self, conn, addr) -> None:
        conn.setblocking(False)
        conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self.counters["socket_accepts"] += 1
        self._anon.append(_Peer(conn, "%s:%d" % addr[:2]))

    # ---- reading ----

    def _read_peer(self, peer: _Peer) -> None:
        if peer.sock is None:
            return
        while True:
            try:
                data = peer.sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop_peer(peer, reset=True)
                return
            if not data:
                self._drop_peer(peer, reset=False)
                return
            self.counters["socket_bytes_recv"] += len(data)
            peer.last_recv = time.monotonic()
            try:
                frames = peer.decoder.feed(data)
            except FrameProtocolError:
                self.counters["socket_protocol_errors"] += 1
                self._drop_peer(peer, reset=True)
                return
            for ftype, payload in frames:
                self.counters["socket_frames_recv"] += 1
                self._on_frame(peer, ftype, payload)
                if peer.sock is None:
                    return

    def _on_frame(self, peer: _Peer, ftype: int, payload: bytes) -> None:
        if ftype == FT_HELLO:
            name = payload.decode(errors="replace")
            old = self._peers.get(name)
            if old is not None and old is not peer:
                self._close_sock(old.sock)    # reconnect replaces
                old.sock = None
            if peer in self._anon:
                self._anon.remove(peer)
            peer.name = name
            self._peers[name] = peer
        elif ftype == FT_MESSAGE:
            try:
                self._ready.append(decode_message(payload))
            except FrameProtocolError:
                self.counters["socket_protocol_errors"] += 1
        elif ftype == FT_PING:
            if peer.name is not None:
                self._enqueue(peer.name, FT_PONG, payload)
        elif ftype == FT_PONG:
            try:
                (token,) = _U32.unpack(payload)
            except struct.error:
                return
            sent = peer.pings.pop(token, None)
            if sent is not None:
                peer.pings.clear()        # any pong proves liveness
                if peer.name is not None:
                    samples = self._rtt.setdefault(peer.name, [])
                    samples.append(time.monotonic() - sent)
                    if len(samples) > 1024:
                        del samples[:512]
        elif ftype == FT_QUERY:
            try:
                q = json.loads(payload.decode())
                fn = self._query_handlers.get(q["dst"])
                result = (fn(q["kind"], q["payload"])
                          if fn is not None else None)
            except Exception:  # noqa: BLE001 — advisory, never fatal
                q, result = None, None
            if q is not None and peer.name is not None:
                self._enqueue(peer.name, FT_QREPLY, json.dumps(
                    {"qid": q["qid"], "result": result},
                    separators=(",", ":")).encode())
        elif ftype == FT_QREPLY:
            try:
                r = json.loads(payload.decode())
                self._qreplies[int(r["qid"])] = r.get("result")
            except Exception:  # noqa: BLE001
                pass

    # ---- pings / half-open ----

    def _ping_sweep(self, now: float) -> None:
        for peer in list(self._peers.values()):
            if peer.sock is None:
                continue
            if peer.pings and now - peer.last_recv > self.half_open_s:
                # we are owed a PONG and the link has been silent past
                # the window: half-open — tear it down (a dial target
                # reconnects; an accepted peer must redial us)
                self.counters["socket_half_open"] += 1
                self._drop_peer(peer, reset=False)
                continue
            if (now - peer.last_recv >= self.ping_interval_s
                    and now - peer.last_ping >= self.ping_interval_s):
                self._ping_seq += 1
                peer.pings[self._ping_seq] = now
                peer.last_ping = now
                self._enqueue(peer.name, FT_PING,
                              _U32.pack(self._ping_seq))

    # ---- writing ----

    def _flush_peer(self, name: str) -> None:
        peer = self._peers.get(name)
        q = self._out.get(name)
        if peer is None or peer.sock is None:
            return
        now = time.monotonic()
        if peer.stall_until > now:
            return
        while True:
            if peer.wbuf:
                try:
                    n = peer.sock.send(peer.wbuf)
                except (BlockingIOError, InterruptedError):
                    return
                except OSError:
                    self._drop_peer(peer, reset=True)
                    return
                self.counters["socket_bytes_sent"] += n
                peer.wbuf = peer.wbuf[n:]
                if peer.wbuf:
                    return                    # kernel buffer full
                if peer.reset_after_wbuf:
                    # FrameChaos reset: mid-frame RST — the receiver
                    # sees a torn frame + connection reset
                    self.counters["socket_resets"] += 1
                    self._drop_peer(peer, reset=False, rst=True)
                    return
            if not q:
                return
            fseq, ftype, payload = q.popleft()
            if self.chaos is not None and ftype == FT_MESSAGE:
                if self.chaos.stall(fseq):
                    peer.stall_until = (time.monotonic()
                                        + self.chaos.stall_s)
                    q.appendleft((fseq, ftype, payload))
                    return
                if self.chaos.corrupt(fseq):
                    payload = _corrupt_frame_payload(payload)
                    self.counters["corrupt_injected"] += 1
                if self.chaos.reset(fseq):
                    block = _frame(ftype, payload)
                    peer.wbuf = block[:max(1, len(block) // 2)]
                    peer.reset_after_wbuf = True
                    self.counters["socket_frames_sent"] += 1
                    continue
            peer.wbuf = _frame(ftype, payload)
            self.counters["socket_frames_sent"] += 1

    # ---- teardown ----

    def _drop_peer(self, peer: _Peer, reset: bool,
                   rst: bool = False) -> None:
        if peer.sock is None:
            return
        if peer.decoder.pending:
            self.counters["socket_torn_frames"] += 1
            peer.decoder = FrameDecoder()
        if reset:
            self.counters["socket_resets"] += 1
        if rst:
            self._rst_close(peer.sock)
        else:
            self._close_sock(peer.sock)
        peer.sock = None
        peer.wbuf = b""
        peer.reset_after_wbuf = False
        peer.pings.clear()
        if peer in self._anon:
            self._anon.remove(peer)
        if peer.name is not None and self._peers.get(peer.name) is peer:
            del self._peers[peer.name]
            d = self._dial.get(peer.name)
            if d is not None:
                # immediate first retry, backoff after (the jitter keys
                # on the attempt counter, so the schedule replays)
                d["next"] = time.monotonic()

    @staticmethod
    def _close_sock(sock) -> None:
        if sock is None:
            return
        try:
            sock.close()
        except OSError:
            pass

    @staticmethod
    def _rst_close(sock) -> None:
        try:
            sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def close(self) -> None:
        """Close every connection and the listener. Idempotent."""
        self._closed = True
        for peer in list(self._peers.values()) + list(self._anon):
            self._close_sock(peer.sock)
            peer.sock = None
        self._peers.clear()
        self._anon.clear()
        for _, conn, _ in self._pending_accepts:
            self._close_sock(conn)
        self._pending_accepts.clear()
        if self._listener is not None:
            self._close_sock(self._listener)
            self._listener = None

    # ---- introspection ----

    def rtt_summary(self) -> dict:
        """Peer round-trip percentiles in seconds (application-level
        ping->pong, so a replica mid-engine-step counts — the honest
        'how stale can my view of this peer be' number)."""
        samples = [s for v in self._rtt.values() for s in v]
        return {"socket_rtt_p50_s": percentile(samples, 50),
                "socket_rtt_p99_s": percentile(samples, 99)}

    def stats(self) -> dict:
        return {**super().stats(), **self.rtt_summary()}
