"""Incubate optimizers (parity: python/paddle/incubate/optimizer/ —
LookAhead lookahead.py:27, ModelAverage modelaverage.py:31).

Both are wrappers over the functional Optimizer interface
(init_state/update over path-keyed dicts), so they compose with TrainStep,
jit, and FSDP sharding exactly like the core optimizers.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k-step lookahead (parity: incubate/optimizer/lookahead.py:27).

    Fast weights follow ``inner_optimizer``; every k steps the slow weights
    move ``alpha`` toward the fast weights and the fast weights reset to the
    slow weights: slow += alpha*(fast - slow); fast = slow.
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._layer = inner_optimizer._layer
        self._param_keys = inner_optimizer._param_keys
        self._lr = inner_optimizer._lr
        self.grad_clip = None
        self.weight_decay = 0.0
        self.multi_precision = inner_optimizer.multi_precision
        self._eager_state = None

    def init_state(self, params):
        return {
            "inner": self.inner_optimizer.init_state(params),
            # copy=True: astype is a no-op for f32 params and the slow slot
            # must NOT alias the (donated) param buffers under TrainStep
            "slow": jax.tree.map(
                lambda p: jnp.array(p, jnp.float32, copy=True), params),
            "step": jnp.zeros((), jnp.int32),
        }

    def update(self, params, grads, state, lr=None):
        fast, inner_state = self.inner_optimizer.update(
            params, grads, state["inner"], lr)
        step = state["step"] + 1
        sync = (step % self.k == 0)
        new_slow = dict(state["slow"])
        new_fast = dict(fast)
        for key in grads:
            if grads[key] is None or key not in state["slow"]:
                continue
            s, p = state["slow"][key], fast[key]
            s_next = s + self.alpha * (p.astype(jnp.float32) - s)
            s_new = jnp.where(sync, s_next, s)
            new_slow[key] = s_new
            new_fast[key] = jnp.where(sync, s_next.astype(p.dtype), p)
        return new_fast, {"inner": inner_state, "slow": new_slow, "step": step}


class ModelAverage(Optimizer):
    """Parameter averaging over a trailing window (parity:
    incubate/optimizer/modelaverage.py:31).

    ``update`` passes parameters through unchanged while accumulating their
    running sum; ``apply()`` swaps the bound layer's parameters for the
    window average (an inference-quality smoother), ``restore()`` swaps back.
    The trailing-window length follows the reference rule
    ``min(max_average_window, max(min_average_window, step *
    average_window_rate))`` — when the accumulator exceeds it, the sum
    restarts from the current parameters, bounding the average's span. The
    reference's three-tier sum_1/sum_2/sum_3 ring buffer exists to bound
    fp32 accumulation error across millions of steps; the single fp32 sum +
    restart is the documented simplification of that mechanism only.
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters,
                         multi_precision=False, name=name)
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._restore_params = None

    def init_state(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "sum": jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                                params),
            "num_accumulates": jnp.zeros((), jnp.int32),
        }

    def update(self, params, grads, state, lr=None):
        step = state["step"] + 1
        num = state["num_accumulates"] + 1
        # reference window rule (modelaverage.py): rate-scaled, clamped
        window = jnp.clip(
            (step.astype(jnp.float32) * self.average_window_rate).astype(jnp.int32),
            self.min_average_window, self.max_average_window)
        restart = num > window
        new_sum = {
            k: jnp.where(restart, params[k].astype(jnp.float32),
                         state["sum"][k] + params[k].astype(jnp.float32))
            for k in state["sum"]
        }
        return dict(params), {
            "step": step,
            "sum": new_sum,
            "num_accumulates": jnp.where(restart, jnp.asarray(1, jnp.int32), num),
        }

    def accumulate(self, params=None):
        """Eager accumulation hook for training loops not using TrainStep."""
        params = params if params is not None else self._bound_params()
        if self._eager_state is None:
            self._eager_state = self.init_state(params)
        _, self._eager_state = self.update(params, {k: True for k in params},
                                           self._eager_state)

    def _window_average(self, state):
        n = jnp.maximum(state["num_accumulates"], 1).astype(jnp.float32)
        return {k: s / n for k, s in state["sum"].items()}

    @contextmanager
    def apply(self, need_restore=True):
        """Swap averaged parameters into the bound layer for evaluation."""
        if self._eager_state is None:
            raise RuntimeError("ModelAverage.apply() before any accumulation")
        params = self._bound_params()
        self._restore_params = dict(params)
        avg = self._window_average(self._eager_state)
        self._layer.set_state_dict(
            {k: avg[k].astype(params[k].dtype) for k in avg})
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._restore_params is not None:
            self._layer.set_state_dict(self._restore_params)
            self._restore_params = None
