"""paddle_tpu.incubate — staging ground for fused/experimental features
(parity: python/paddle/incubate/, SURVEY §A.5 fused LLM layer zoo)."""

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401
