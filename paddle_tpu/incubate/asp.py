"""ASP — automatic structured (n:m) sparsity utilities
(parity: python/paddle/incubate/asp/ — create_mask utils.py, prune_model,
calculate_density supported_layer_list).

The reference targets NVIDIA 2:4 sparse tensor cores; TPUs have no sparse
MXU mode, so the VALUE here is the pruning workflow (train → prune → mask is
preserved by masked grads), not a kernel speedup. Masks are computed with the
same greedy largest-magnitude n-of-m rule, and ``decorate``-style enforcement
is a multiply — XLA fuses it into the consumer matmul. Documented
deprioritization: no sparse-format storage or sparse kernel dispatch.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["create_mask", "calculate_density", "check_mask", "prune_model",
           "apply_masks"]


def create_mask(w, n=2, m=4):
    """Keep the n largest-|w| entries of every m consecutive elements of the
    last axis (parity: asp create_mask with MaskAlgo.MASK_1D best-effort)."""
    w = jnp.asarray(w)
    if w.shape[-1] % m:
        raise ValueError(f"last dim {w.shape[-1]} not divisible by m={m}")
    groups = w.reshape(w.shape[:-1] + (w.shape[-1] // m, m))
    order = jnp.argsort(-jnp.abs(groups), axis=-1)
    ranks = jnp.argsort(order, axis=-1)  # rank of each element within group
    mask = (ranks < n).astype(w.dtype)
    return mask.reshape(w.shape)


def calculate_density(x):
    x = jnp.asarray(x)
    return float(jnp.mean((x != 0).astype(jnp.float32)))


def check_mask(w, n=2, m=4):
    """True iff every m-group of w has at most n nonzeros."""
    w = jnp.asarray(w)
    groups = w.reshape(w.shape[:-1] + (w.shape[-1] // m, m))
    nnz = jnp.sum((groups != 0).astype(jnp.int32), axis=-1)
    return bool(jnp.all(nnz <= n))


def prune_model(layer, n=2, m=4, min_ndim=2):
    """Apply n:m masks to every >=2-D parameter whose last dim divides m.

    Returns {param_path: mask}; reapply after each optimizer step with
    :func:`apply_masks` (the reference's OptimizerWithSparsityGuarantee)."""
    masks = {}
    params = layer.param_dict(trainable_only=True)
    pruned = {}
    for k, w in params.items():
        if w.ndim >= min_ndim and w.shape[-1] % m == 0:
            mask = create_mask(w, n, m)
            masks[k] = mask
            pruned[k] = w * mask
    layer.set_state_dict(pruned)
    return masks


def apply_masks(params, masks):
    """params with masks re-applied (post-update sparsity enforcement)."""
    return {k: (p * masks[k] if k in masks else p) for k, p in params.items()}
