"""Fused functional surface (parity: python/paddle/incubate/nn/functional/ —
fused_rms_norm.py, fused_layer_norm.py, fused_rotary_position_embedding.py,
swiglu.py, fused_matmul_bias.py, fused_dropout_add.py,
masked_multihead_attention.py, block_multihead_attention.py,
variable_length_memory_efficient_attention.py).

TPU mapping: norms hit the Pallas one-pass kernels; rope/swiglu/matmul-bias
are XLA compositions that the compiler provably fuses into the surrounding
matmuls (they exist here for API parity and as the single place the fusion
contract is tested); decode attention is gather+einsum shaped for the MXU
with length masking; varlen attention is the segment-masked flash kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ....ops.pallas.fused_norm import fused_rms_norm as _rms_pallas
from ....ops.pallas.fused_norm import fused_layer_norm as _ln_pallas
from ....ops.pallas.flash_attention import flash_attn_unpadded

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
    "swiglu", "fused_linear", "fused_matmul_bias", "fused_dropout_add",
    "fused_bias_dropout_residual_layer_norm", "masked_multihead_attention",
    "block_multihead_attention", "variable_length_memory_efficient_attention",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon: float = 1e-6,
                   begin_norm_axis: int = -1, bias=None, residual=None):
    """Parity: incubate fused_rms_norm — optional bias+residual add fused in
    front of the norm; returns (out, residual_out) when residual is given."""
    pre = x
    if bias is not None:
        pre = pre + bias
    if residual is not None:
        pre = pre + residual
    out = _rms_pallas(pre, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    if residual is not None:
        return out, pre
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon: float = 1e-5,
                     begin_norm_axis: int = -1, bias=None, residual=None):
    pre = x
    if bias is not None:
        pre = pre + bias
    if residual is not None:
        pre = pre + residual
    out = _ln_pallas(pre, norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, pre
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style: bool = True):
    """Parity: incubate fused_rotary_position_embedding. q/k/v:
    [b, s, h, d]; cos/sin: [S, d/2] (or [S, d] — the half is used). Rotates
    q and k (v passes through, matching the reference contract)."""
    def rot(x):
        if x is None:
            return None
        b, s, h, d = x.shape
        c, si = cos, sin
        if c.shape[-1] == d:
            c = c[..., : d // 2]
            si = si[..., : d // 2]
        if position_ids is None:
            cc = c[:s][None, :, None, :]
            ss = si[:s][None, :, None, :]
        else:
            cc = jnp.take(c, position_ids, axis=0)[:, :, None, :]
            ss = jnp.take(si, position_ids, axis=0)[:, :, None, :]
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x, 2, axis=-1)
        else:  # interleaved (GPT-J style)
            x1, x2 = x[..., 0::2], x[..., 1::2]
        xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
        o1 = xf1 * cc - xf2 * ss
        o2 = xf2 * cc + xf1 * ss
        if use_neox_rotary_style:
            out = jnp.concatenate([o1, o2], axis=-1)
        else:
            out = jnp.stack([o1, o2], axis=-1).reshape(x.shape)
        return out.astype(x.dtype)

    return rot(q), rot(k), v


def swiglu(x, y=None):
    """Parity: incubate swiglu — silu(x) * y; with y=None, x is split in
    half on the last axis. XLA fuses this into the surrounding matmuls."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def fused_linear(x, weight, bias=None, transpose_weight: bool = False):
    """Parity: incubate fused_matmul_bias/FusedLinear — XLA fuses the bias
    epilogue onto the MXU matmul (the cublasLt epilogue equivalent)."""
    w = weight.T if transpose_weight else weight
    out = x @ w
    if bias is not None:
        out = out + bias
    return out


fused_matmul_bias = fused_linear


def fused_dropout_add(x, y, p: float = 0.5, training: bool = True,
                      mode: str = "upscale_in_train", key=None):
    """Parity: incubate fused_dropout_add — dropout(x) + y in one fused op."""
    if not training or p == 0.0:
        return x + y
    from ....core import rng as _rng
    key = key if key is not None else _rng.next_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0) + y
    return jnp.where(keep, x, 0.0) + y


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate: float = 0.5,
                                           ln_epsilon: float = 1e-5,
                                           training: bool = True, key=None):
    """Parity: incubate FusedBiasDropoutResidualLayerNorm (functional)."""
    pre = x if bias is None else x + bias
    pre = fused_dropout_add(pre, residual, p=dropout_rate, training=training,
                            key=key)
    d = pre.shape[-1]
    scale = ln_scale if ln_scale is not None else jnp.ones((d,), pre.dtype)
    shift = ln_bias if ln_bias is not None else jnp.zeros((d,), pre.dtype)
    return _ln_pallas(pre, scale, shift, ln_epsilon)


# ---------------- decode-time attention ----------------

def masked_multihead_attention(q, k_new, v_new, cache_k, cache_v, seq_lens,
                               scale: float | None = None):
    """Decode-step attention over a fixed-size KV cache (parity: incubate
    masked_multihead_attention.py — the per-token decode kernel).

    q/k_new/v_new: [b, 1, h(kvh), d] — this step's projections.
    cache_k/v: [b, S_max, kvh, d] (fp, or int8 QuantizedKV — the step
    token is quantized HERE, at cache-write time, codes + scale row);
    seq_lens: [b] tokens already cached.
    Writes the new k/v at position seq_lens, then attends q over positions
    <= seq_lens. GQA supported (q heads a multiple of cache kv heads).
    Returns (out [b, 1, h, d], cache_k, cache_v) — caches functionally
    updated (donate/alias under jit for in-place HBM update).
    """
    from ....quantization.serving import QuantizedKV, kv_quantize
    b, _, h, d = q.shape
    kvh = cache_k.shape[2]
    S = cache_k.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    bidx = jnp.arange(b)
    if isinstance(cache_k, QuantizedKV):
        kq = kv_quantize(k_new[:, 0])          # codes [b,kvh,d], scale [b,kvh]
        vq = kv_quantize(v_new[:, 0])
        cache_k = QuantizedKV(cache_k.q.at[bidx, seq_lens].set(kq.q),
                              cache_k.scale.at[bidx, seq_lens].set(kq.scale))
        cache_v = QuantizedKV(cache_v.q.at[bidx, seq_lens].set(vq.q),
                              cache_v.scale.at[bidx, seq_lens].set(vq.scale))
    else:
        cache_k = cache_k.at[bidx, seq_lens].set(
            k_new[:, 0].astype(cache_k.dtype))
        cache_v = cache_v.at[bidx, seq_lens].set(
            v_new[:, 0].astype(cache_v.dtype))
    out = _grouped_decode_attn(q, cache_k, cache_v, seq_lens, scale)
    return out, cache_k, cache_v


def _grouped_decode_attn(q, kc, vc, seq_lens, scale):
    """GQA decode core — shared with the paged serving path; lives in
    nn.functional.attention so contiguous and block-table decode stay one
    implementation (bit-identical tokens either way)."""
    from ....nn.functional.attention import _grouped_decode_attn as _core
    return _core(q, kc, vc, seq_lens, scale)


def block_multihead_attention(q, pool_k, pool_v, block_tables, seq_lens,
                              k_new=None, v_new=None,
                              scale: float | None = None):
    """Decode attention over a PAGED (blocked) KV cache (parity: incubate
    block_multihead_attention.py — the paged-attention decode path).

    Pages live in a shared pool; each sequence owns a list of pages:
      pool_k/pool_v: [num_blocks, block_size, kvh, d]
      block_tables:  [b, max_blocks_per_seq] int32 page ids
      seq_lens:      [b] tokens already cached
    With k_new/v_new [b, 1, kvh, d], the step's KV is first written into the
    page at position seq_lens (pages must be pre-allocated in block_tables).
    Returns (out [b, 1, h, d], pool_k, pool_v).
    """
    from ....quantization.serving import QuantizedKV, kv_quantize
    b, _, h, d = q.shape
    nb, bs, kvh, _ = pool_k.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    if k_new is not None:
        blk = jnp.take_along_axis(block_tables, (seq_lens // bs)[:, None],
                                  axis=1)[:, 0]
        if isinstance(pool_k, QuantizedKV):
            kq = kv_quantize(k_new[:, 0])
            vq = kv_quantize(v_new[:, 0])
            off = seq_lens % bs
            pool_k = QuantizedKV(
                pool_k.q.at[blk, off].set(kq.q),
                pool_k.scale.at[blk, off].set(kq.scale))
            pool_v = QuantizedKV(
                pool_v.q.at[blk, off].set(vq.q),
                pool_v.scale.at[blk, off].set(vq.scale))
        else:
            pool_k = pool_k.at[blk, seq_lens % bs].set(
                k_new[:, 0].astype(pool_k.dtype))
            pool_v = pool_v.at[blk, seq_lens % bs].set(
                v_new[:, 0].astype(pool_v.dtype))
    # gather + grouped-GQA attention, shared with the serving engine
    # (Pallas block-table kernel on TPU, XLA gather elsewhere)
    from ....nn.functional.attention import paged_attention_decode
    out = paged_attention_decode(q, pool_k, pool_v, block_tables, seq_lens,
                                 scale=scale)
    return out, pool_k, pool_v


def variable_length_memory_efficient_attention(q, k, v, cu_seqlens_q,
                                               cu_seqlens_k,
                                               causal: bool = False,
                                               scale: float | None = None):
    """Parity: incubate variable_length_memory_efficient_attention — routed
    to the segment-masked Pallas flash kernel (flash_attn_unpadded)."""
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               causal=causal, scale=scale)
