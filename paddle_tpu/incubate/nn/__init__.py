"""Fused transformer layer zoo (parity: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention :189, FusedFeedForward :483,
FusedTransformerEncoderLayer :697, FusedMultiTransformer :994,
FusedBiasDropoutResidualLayerNorm :83 — and layer/fused_linear.py).

TPU design: each layer is a thin Module over the fused functional surface
(incubate.nn.functional) — Pallas norms, flash/decode attention kernels, and
XLA-fused epilogues — rather than a monolithic C++ kernel: under jit the
whole block compiles into the same fused program the reference hand-writes.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...nn.module import Layer, Parameter
from ...nn import initializer as I
from . import functional as FF

__all__ = [
    "FusedLinear", "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedMultiTransformer",
    "FusedBiasDropoutResidualLayerNorm",
]


class FusedLinear(Layer):
    """Parity: incubate FusedLinear — bias epilogue fused onto the matmul."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        w_init = weight_attr if callable(weight_attr) else I.XavierNormal()
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = Parameter(w_init(shape, self._dtype))
        self.transpose_weight = transpose_weight
        if bias_attr is False:
            self.bias = None
        else:
            b_init = bias_attr if callable(bias_attr) else I.Constant(0.0)
            self.bias = Parameter(b_init((out_features,), self._dtype))

    def forward(self, x):
        return FF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self.transpose_weight)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """Parity: fused_transformer.py:83."""

    def __init__(self, embed_dim, dropout_rate=0.5, epsilon=1e-5, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.ln_scale = Parameter(I.Constant(1.0)((embed_dim,), self._dtype))
        self.ln_bias = Parameter(I.Constant(0.0)((embed_dim,), self._dtype))

    def forward(self, x, residual):
        return FF.fused_bias_dropout_residual_layer_norm(
            x, residual, ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self.epsilon,
            training=self.training)


class FusedMultiHeadAttention(Layer):
    """Parity: fused_transformer.py:189 — pre/post-LN MHA block with fused
    qkv projection, flash attention core, and fused residual+dropout+LN."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 epsilon=1e-5, name=None, mp_axis=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.qkv_proj = nn.Linear(embed_dim, 3 * embed_dim,
                                  weight_spec=(None, mp_axis))
        self.out_proj = nn.Linear(embed_dim, embed_dim,
                                  weight_spec=(mp_axis, None))
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, query, key=None, value=None, attn_mask=None):
        x = query
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        b, s, e = x.shape
        qkv = self.qkv_proj(x).reshape(b, s, 3, self.num_heads, self.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = self.out_proj(out.reshape(b, s, e))
        out = FF.fused_dropout_add(out, residual, p=self.dropout_rate,
                                   training=self.training)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    """Parity: fused_transformer.py:483."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, name=None, mp_axis=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (act_dropout_rate if act_dropout_rate
                                 is not None else dropout_rate)
        self.activation = activation
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 weight_spec=(None, mp_axis))
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 weight_spec=(mp_axis, None))
        self.norm = nn.LayerNorm(d_model, epsilon=epsilon)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        act = getattr(F, self.activation)
        h = act(self.linear1(x))
        h = F.dropout(h, p=self.act_dropout_rate, training=self.training)
        h = self.linear2(h)
        out = FF.fused_dropout_add(h, residual, p=self.dropout_rate,
                                   training=self.training)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """Parity: fused_transformer.py:697."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, name=None):
        super().__init__()
        self.self_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate if attn_dropout_rate
                               is not None else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.self_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """Parity: fused_transformer.py:994 — the full fused decoder stack with
    a KV-cache path, the reference's LLM-inference workhorse.

    Pre-norm decoder blocks (LN -> attention -> LN -> FFN, residuals), GQA
    via num_key_value_heads. Three modes:
      - ``forward(x)``: training/prefill without cache (flash attention);
      - ``forward(x, caches=..., seq_lens=...)``: single-token decode step
        through ``masked_multihead_attention`` over fixed-size caches;
      - norm kernels are the Pallas fused norms.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, num_layers=1,
                 dropout_rate=0.0, activation="gelu", epsilon=1e-5,
                 num_key_value_heads=None, normalize_before=True, name=None):
        super().__init__()
        assert normalize_before, "FusedMultiTransformer is pre-norm"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.kv_heads = num_key_value_heads or num_heads
        self.head_dim = embed_dim // num_heads
        self.activation = activation
        self.epsilon = epsilon
        self.num_layers = num_layers
        self.dropout_rate = dropout_rate
        h, kvh, d = num_heads, self.kv_heads, self.head_dim
        for i in range(num_layers):
            self.add_sublayer(f"ln1_{i}", nn.LayerNorm(embed_dim, epsilon))
            self.add_sublayer(f"q_{i}", nn.Linear(embed_dim, h * d,
                                                  bias_attr=False))
            self.add_sublayer(f"kv_{i}", nn.Linear(embed_dim, 2 * kvh * d,
                                                   bias_attr=False))
            self.add_sublayer(f"o_{i}", nn.Linear(h * d, embed_dim,
                                                  bias_attr=False))
            self.add_sublayer(f"ln2_{i}", nn.LayerNorm(embed_dim, epsilon))
            self.add_sublayer(f"ff1_{i}", nn.Linear(embed_dim,
                                                    dim_feedforward))
            self.add_sublayer(f"ff2_{i}", nn.Linear(dim_feedforward,
                                                    embed_dim))

    def _layer(self, i):
        g = lambda n: getattr(self, f"{n}_{i}")  # noqa: E731
        return (g("ln1"), g("q"), g("kv"), g("o"), g("ln2"), g("ff1"),
                g("ff2"))

    def init_caches(self, batch_size, max_len, dtype=None):
        dtype = dtype or jnp.bfloat16
        shape = (batch_size, max_len, self.kv_heads, self.head_dim)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(self.num_layers)]

    def forward(self, x, attn_mask=None, caches=None, seq_lens=None):
        b, s, e = x.shape
        h, kvh, d = self.num_heads, self.kv_heads, self.head_dim
        act = getattr(F, self.activation)
        new_caches = []
        for i in range(self.num_layers):
            ln1, q_p, kv_p, o_p, ln2, ff1, ff2 = self._layer(i)
            res = x
            hdn = FF.fused_layer_norm(x, ln1.weight, ln1.bias, self.epsilon)
            q = q_p(hdn).reshape(b, s, h, d)
            kv = kv_p(hdn).reshape(b, s, 2, kvh, d)
            k, v = kv[:, :, 0], kv[:, :, 1]
            if caches is not None:
                assert s == 1, "cache path is single-token decode"
                out, ck, cv = FF.masked_multihead_attention(
                    q, k, v, caches[i][0], caches[i][1], seq_lens)
                new_caches.append((ck, cv))
            else:
                if kvh != h:
                    k = jnp.repeat(k, h // kvh, axis=2)
                    v = jnp.repeat(v, h // kvh, axis=2)
                out = F.scaled_dot_product_attention(
                    q, k, v, attn_mask=attn_mask, is_causal=True,
                    training=self.training)
            x = res + o_p(out.reshape(b, s, h * d))
            res = x
            hdn = FF.fused_layer_norm(x, ln2.weight, ln2.bias, self.epsilon)
            x = res + ff2(act(ff1(hdn)))
        if caches is not None:
            return x, new_caches
        return x
