"""LLM serving front-end (the Predictor analogue for generative decode).

``create_predictor`` serves fixed-shape programs; LLM serving is the
opposite regime — ragged prompts arriving over time, each wanting its
own decode length and sampling. ``LLMPredictor`` closes that gap by
fronting the continuous-batching engine in ``paddle_tpu.serving``: a
Predictor-shaped object (create → feed → fetch) whose ``generate`` runs
every prompt through one paged KV pool with iteration-level scheduling,
and whose ``stream`` exposes tokens as they decode.
"""

from __future__ import annotations

import numpy as np

__all__ = ["LLMPredictor", "create_llm_predictor"]


class LLMPredictor:
    """Batch-of-prompts front door over ``serving.ServingEngine``.

    Unlike ``generate()`` on the model (one fixed-shape batch, padded to
    the longest prompt), requests here share the paged pool: no padding
    waste, arrivals can be staggered, and greedy outputs are bitwise
    identical to per-request ``model.generate`` (SERVING.md).
    """

    def __init__(self, model, num_pages: int = 128, page_size: int = 16,
                 max_slots: int = 8, max_pages_per_slot: int | None = None,
                 prefill_token_budget: int = 2048, kv_dtype=None,
                 clock=None):
        from ..serving import ServingEngine
        self.model = model
        self._mk = lambda: ServingEngine(
            model, num_pages=num_pages, page_size=page_size,
            max_slots=max_slots, max_pages_per_slot=max_pages_per_slot,
            prefill_token_budget=prefill_token_budget, kv_dtype=kv_dtype,
            clock=clock)
        self.engine = self._mk()

    def generate(self, prompts, max_new_tokens: int = 32,
                 eos_token_id: int | None = None, sampling=None,
                 max_steps: int | None = None):
        """Run a batch of ragged prompts to completion; returns a list of
        generated-token lists in prompt order. ``sampling`` is one
        SamplingParams for all, or a per-prompt list."""
        if sampling is not None and isinstance(sampling, (list, tuple)):
            if len(sampling) != len(prompts):
                raise ValueError(
                    f"{len(sampling)} sampling params for "
                    f"{len(prompts)} prompts")
            per = list(sampling)
        else:
            per = [sampling] * len(prompts)
        rids = [self.engine.add_request(np.asarray(p).reshape(-1),
                                        max_new_tokens, sampling=sp,
                                        eos_token_id=eos_token_id)
                for p, sp in zip(prompts, per)]
        results = self.engine.run_to_completion(max_steps=max_steps)
        return [results[rid] for rid in rids]

    def stream(self, prompts, max_new_tokens: int = 32,
               eos_token_id: int | None = None, sampling=None):
        """Token-at-a-time iterator: yields ``{"index", "rid", "token",
        "finished", "finish_reason"}`` with ``index`` the prompt's
        position in the input batch."""
        rids = [self.engine.add_request(np.asarray(p).reshape(-1),
                                        max_new_tokens, sampling=sampling,
                                        eos_token_id=eos_token_id)
                for p in prompts]
        pos = {rid: i for i, rid in enumerate(rids)}
        for ev in self.engine.stream():
            if ev["rid"] in pos:
                yield {"index": pos[ev["rid"]], **ev}

    def metrics_summary(self) -> dict:
        return self.engine.metrics.summary()

    def stats(self) -> dict:
        return self.engine.stats()

    def reset(self) -> None:
        """Fresh engine: drops metrics and the request table. Prefer one
        long-lived predictor — a new engine builds a new decode program."""
        self.engine = self._mk()


def create_llm_predictor(model, **kw) -> LLMPredictor:
    return LLMPredictor(model, **kw)
