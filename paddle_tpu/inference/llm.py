"""LLM serving front-end (the Predictor analogue for generative decode).

``create_predictor`` serves fixed-shape programs; LLM serving is the
opposite regime — ragged prompts arriving over time, each wanting its
own decode length and sampling. ``LLMPredictor`` closes that gap by
fronting the continuous-batching engine in ``paddle_tpu.serving``: a
Predictor-shaped object (create → feed → fetch) whose ``generate`` runs
every prompt through one paged KV pool with iteration-level scheduling,
and whose ``stream`` exposes tokens as they decode.
"""

from __future__ import annotations

import numpy as np

__all__ = ["LLMPredictor", "create_llm_predictor"]


class LLMPredictor:
    """Batch-of-prompts front door over ``serving.ServingEngine``.

    Unlike ``generate()`` on the model (one fixed-shape batch, padded to
    the longest prompt), requests here share the paged pool: no padding
    waste, arrivals can be staggered, and greedy outputs are bitwise
    identical to per-request ``model.generate`` (SERVING.md).
    """

    def __init__(self, model, num_pages: int = 128, page_size: int = 16,
                 max_slots: int = 8, max_pages_per_slot: int | None = None,
                 prefill_token_budget: int = 2048, kv_dtype=None,
                 clock=None, max_queue_depth: int | None = None,
                 max_preemptions: int | None = None,
                 step_timeout_s: float | None = None,
                 drain_timeout_s: float | None = 30.0,
                 prefix_cache: bool = True, kv_quant: bool = False,
                 weight_quant: bool = False):
        from ..serving import ServingEngine
        if weight_quant:
            # int8 weight streaming (SERVING.md "Quantized KV & weights"):
            # decode matmuls stream int8 codes + per-channel scales and
            # dequantize in the matmul epilogue — ~half the weight bytes
            # of bf16 per decode step
            from ..quantization.serving import quantize_for_serving
            model = quantize_for_serving(model)
        self.model = model
        self._mk = lambda: ServingEngine(
            model, num_pages=num_pages, page_size=page_size,
            max_slots=max_slots, max_pages_per_slot=max_pages_per_slot,
            prefill_token_budget=prefill_token_budget, kv_dtype=kv_dtype,
            clock=clock, max_queue_depth=max_queue_depth,
            max_preemptions=max_preemptions, step_timeout_s=step_timeout_s,
            drain_timeout_s=drain_timeout_s, prefix_cache=prefix_cache,
            kv_quant=kv_quant)
        self.engine = self._mk()

    #: typed serving error -> the stable ``error`` string reported by
    #: :meth:`generate_detailed` (documented in SERVING.md "Serving
    #: failure modes"; the set is append-only — callers may switch on it)
    FAILURE_CODES = {
        "QueueFullError": "queue_full",
        "RequestTooLargeError": "too_large",
        "EngineDrainingError": "draining",
        "SchedulerStalledError": "scheduler_stalled",
        "FleetOverloadedError": "overloaded",
    }

    def generate(self, prompts, max_new_tokens: int = 32,
                 eos_token_id: int | None = None, sampling=None,
                 max_steps: int | None = None):
        """Run a batch of ragged prompts to completion; returns a list of
        generated-token lists in prompt order. ``sampling`` is one
        SamplingParams for all, or a per-prompt list. Raises the typed
        serving errors (QueueFullError / RequestTooLargeError /
        EngineDrainingError / SchedulerStalledError) — use
        :meth:`generate_detailed` for per-prompt failure results
        instead of exceptions."""
        if sampling is not None and isinstance(sampling, (list, tuple)):
            if len(sampling) != len(prompts):
                raise ValueError(
                    f"{len(sampling)} sampling params for "
                    f"{len(prompts)} prompts")
            per = list(sampling)
        else:
            per = [sampling] * len(prompts)
        rids = [self.engine.add_request(np.asarray(p).reshape(-1),
                                        max_new_tokens, sampling=sp,
                                        eos_token_id=eos_token_id)
                for p, sp in zip(prompts, per)]
        results = self.engine.run_to_completion(max_steps=max_steps)
        return [results[rid] for rid in rids]

    def generate_detailed(self, prompts, max_new_tokens: int = 32,
                          eos_token_id: int | None = None, sampling=None,
                          deadline_s: float | None = None,
                          max_queue_wait_s: float | None = None,
                          max_steps: int | None = None):
        """Like :meth:`generate`, but every typed serving failure becomes
        a stable per-prompt result instead of an exception. Returns one
        dict per prompt, in order:

        ``{"tokens": [...], "finish_reason": str | None, "error":
        None | "queue_full" | "too_large" | "draining" |
        "scheduler_stalled" | "overloaded", "retryable": bool}``

        Rejected prompts carry ``finish_reason="rejected"`` and empty
        tokens; accepted prompts carry the engine's classified
        finish_reason (``stop`` / ``length`` / ``timeout`` /
        ``nonfinite`` / ``preempted`` / ``preempted_limit`` /
        ``injected`` — SERVING.md). A scheduler stall marks every
        still-unfinished prompt ``scheduler_stalled`` rather than
        raising. ``retryable`` surfaces the typed error's own
        ``ServingError.retryable`` flag, so a transient shed
        (queue_full / draining / overloaded — back off and resubmit,
        possibly elsewhere) is machine-distinguishable from a terminal
        rejection (too_large: every homogeneous replica refuses it
        identically, retrying is futile)."""
        from ..serving import SchedulerStalledError, ServingError
        if sampling is not None and isinstance(sampling, (list, tuple)):
            per = list(sampling)
        else:
            per = [sampling] * len(prompts)
        outcomes = [None] * len(prompts)
        rids: dict[str, int] = {}
        for i, (p, sp) in enumerate(zip(prompts, per)):
            try:
                rid = self.engine.add_request(
                    np.asarray(p).reshape(-1), max_new_tokens, sampling=sp,
                    eos_token_id=eos_token_id, deadline_s=deadline_s,
                    max_queue_wait_s=max_queue_wait_s)
                rids[rid] = i
            except ServingError as e:
                outcomes[i] = {"tokens": [], "finish_reason": "rejected",
                               "error": self.FAILURE_CODES.get(
                                   type(e).__name__, "serving_error"),
                               "retryable": bool(e.retryable)}
        stalled = False
        try:
            self.engine.run_to_completion(max_steps=max_steps)
        except SchedulerStalledError:
            stalled = True
        for rid, i in rids.items():
            req = self.engine.request(rid)
            if req.finish_reason is None:
                # SchedulerStalledError.retryable is True: a stall is
                # an engine-side livelock, not the request's fault
                outcomes[i] = {"tokens": list(req.tokens),
                               "finish_reason": "stalled" if stalled
                               else None,
                               "error": "scheduler_stalled" if stalled
                               else None,
                               "retryable": stalled}
            else:
                outcomes[i] = {"tokens": list(req.tokens),
                               "finish_reason": req.finish_reason,
                               "error": None,
                               # matches drain()'s retriable contract:
                               # only a preempted eviction computed
                               # nothing the client is owed elsewhere
                               "retryable": req.finish_reason
                               == "preempted"}
        return outcomes

    def drain(self, timeout_s: float | None = None) -> dict:
        """Graceful shutdown passthrough: ``engine.drain`` — stops
        admission and reports per-request outcomes (SERVING.md)."""
        return self.engine.drain(timeout_s=timeout_s)

    def stream(self, prompts, max_new_tokens: int = 32,
               eos_token_id: int | None = None, sampling=None):
        """Token-at-a-time iterator: yields ``{"index", "rid", "token",
        "finished", "finish_reason"}`` with ``index`` the prompt's
        position in the input batch."""
        rids = [self.engine.add_request(np.asarray(p).reshape(-1),
                                        max_new_tokens, sampling=sampling,
                                        eos_token_id=eos_token_id)
                for p in prompts]
        pos = {rid: i for i, rid in enumerate(rids)}
        for ev in self.engine.stream():
            if ev["rid"] in pos:
                yield {"index": pos[ev["rid"]], **ev}

    def metrics_summary(self) -> dict:
        """Engine metrics incl. the prefix-cache view: ``cache_hit_rate``
        (fraction of prefill context tokens served from cached pages)
        plus the pool's lookup/hit/eviction/COW counters (SERVING.md
        "Prefix caching")."""
        return self.engine.metrics.summary()

    def stats(self) -> dict:
        return self.engine.stats()

    def reset(self) -> None:
        """Fresh engine: drops metrics and the request table. Prefer one
        long-lived predictor — a new engine builds a new decode program."""
        self.engine = self._mk()


def create_llm_predictor(model, **kw) -> LLMPredictor:
    return LLMPredictor(model, **kw)
