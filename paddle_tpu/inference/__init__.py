"""paddle_tpu.inference — deployment API (parity: paddle.inference
Config/create_predictor over AnalysisPredictor,
fluid/inference/api/analysis_predictor.cc:1423).

TPU-native collapse: the reference's analysis passes (fusion, subgraph
offload, memory optimization) are XLA's job; what remains is the loading +
serving contract: load a source-free artifact, expose named IO, run
batches. The artifact is the StableHLO export from ``paddle_tpu.jit.save``.
"""

from __future__ import annotations

import numpy as np

from ..jit.save_load import load as _load

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """Parity: paddle.inference.Config — model path + runtime knobs. Device
    placement is jax's; the knobs kept are the ones with TPU meaning."""

    def __init__(self, prog_file_or_prefix: str, params_file: str | None = None):
        prefix = prog_file_or_prefix
        if prefix.endswith(".pdmodel"):
            prefix = prefix[: -len(".pdmodel")]
        self.prefix = prefix
        self._memory_optim = True

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def model_dir(self):
        return self.prefix


class Predictor:
    """Parity: paddle_infer.Predictor — named-handle IO over the loaded
    program."""

    def __init__(self, config: Config):
        self._layer = _load(config.prefix)
        self._inputs = [None] * len(self._layer.input_shapes)

    def get_input_names(self):
        return [f"input_{i}" for i in range(len(self._inputs))]

    def get_input_handle(self, name: str):
        idx = int(name.split("_")[-1])
        pred = self

        class _Handle:
            def copy_from_cpu(self, arr):
                pred._inputs[idx] = np.asarray(arr)

            def reshape(self, shape):
                pass

        return _Handle()

    def run(self, inputs=None):
        args = inputs if inputs is not None else self._inputs
        if any(a is None for a in args):
            raise ValueError("inputs not set; pass them to run() or via "
                             "get_input_handle().copy_from_cpu")
        out = self._layer(*args)
        self._outputs = out if isinstance(out, (tuple, list)) else [out]
        return [np.asarray(o) for o in self._outputs]

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(getattr(self, "_outputs", [0])))]

    def get_output_handle(self, name: str):
        idx = int(name.split("_")[-1])
        pred = self

        class _Handle:
            def copy_to_cpu(self):
                return np.asarray(pred._outputs[idx])

        return _Handle()


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
