"""paddle_tpu.inference — deployment API (parity: paddle.inference
Config/create_predictor over AnalysisPredictor,
fluid/inference/api/analysis_predictor.cc:1423).

TPU-native collapse: the reference's analysis passes (fusion, subgraph
offload, memory optimization) are XLA's job; what remains is the loading +
serving contract: load a source-free artifact, expose named IO, run
batches. The artifact is the StableHLO export from ``paddle_tpu.jit.save``.

``paddle_tpu.inference.llm`` adds the LLM serving front-end: an
``LLMPredictor`` over the continuous-batching engine in
``paddle_tpu.serving`` (see SERVING.md).
"""

from __future__ import annotations

import os

import numpy as np

from ..jit.save_load import load as _load
from .llm import LLMPredictor, create_llm_predictor  # noqa: F401

__all__ = ["Config", "Predictor", "create_predictor",
           "LLMPredictor", "create_llm_predictor"]

_ARTIFACT_SUFFIXES = (".pdmodel", ".pdiparams", ".pdmeta")


class Config:
    """Parity: paddle.inference.Config — model path + runtime knobs. Device
    placement is jax's; the knobs kept are the ones with TPU meaning."""

    def __init__(self, prog_file_or_prefix: str, params_file: str | None = None):
        prefix = prog_file_or_prefix
        if prefix.endswith(".pdmodel"):
            prefix = prefix[: -len(".pdmodel")]
        self.prefix = prefix
        self._memory_optim = True

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def model_dir(self):
        return self.prefix


class Predictor:
    """Parity: paddle_infer.Predictor — named-handle IO over the loaded
    program."""

    def __init__(self, config: Config):
        missing = [config.prefix + s for s in _ARTIFACT_SUFFIXES
                   if not os.path.exists(config.prefix + s)]
        if missing:
            raise FileNotFoundError(
                f"no saved model at prefix {config.prefix!r}: missing "
                f"{missing} (artifacts are written by paddle_tpu.jit.save)")
        self._layer = _load(config.prefix)
        self._inputs = [None] * len(self._layer.input_shapes)

    def get_input_names(self):
        return [f"input_{i}" for i in range(len(self._inputs))]

    def _input_index(self, name: str) -> int:
        names = self.get_input_names()
        if name not in names:
            raise KeyError(f"unknown input name {name!r}; this model's "
                           f"inputs are {names}")
        return names.index(name)

    def get_input_handle(self, name: str):
        idx = self._input_index(name)
        pred = self

        class _Handle:
            def copy_from_cpu(self, arr):
                pred._inputs[idx] = pred._check_input(idx, np.asarray(arr))

            def reshape(self, shape):
                pass

        return _Handle()

    def _check_input(self, idx: int, arr: np.ndarray) -> np.ndarray:
        """Validate against the saved meta — XLA export traced STATIC
        shapes, so a mismatch here would otherwise surface as an opaque
        StableHLO call error."""
        want_shape = tuple(self._layer.input_shapes[idx])
        want_dtype = np.dtype(self._layer.input_dtypes[idx])
        if tuple(arr.shape) != want_shape:
            raise ValueError(
                f"input_{idx}: shape mismatch — the saved program was "
                f"exported for {want_shape}, got {tuple(arr.shape)} "
                f"(shapes are static under XLA export; re-export with the "
                f"serving shape)")
        if arr.dtype != want_dtype:
            raise TypeError(
                f"input_{idx}: dtype mismatch — the saved program was "
                f"exported for {want_dtype}, got {arr.dtype}")
        return arr

    def run(self, inputs=None):
        if inputs is not None:
            if len(inputs) != len(self._inputs):
                raise ValueError(
                    f"model takes {len(self._inputs)} inputs, got "
                    f"{len(inputs)}")
            args = [self._check_input(i, np.asarray(a))
                    for i, a in enumerate(inputs)]
        else:
            unset = [f"input_{i}" for i, a in enumerate(self._inputs)
                     if a is None]
            if unset:
                raise ValueError(f"inputs not set: {unset}; pass them to "
                                 f"run() or via "
                                 f"get_input_handle().copy_from_cpu")
            args = self._inputs
        out = self._layer(*args)
        self._outputs = out if isinstance(out, (tuple, list)) else [out]
        return [np.asarray(o) for o in self._outputs]

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(getattr(self, "_outputs", [0])))]

    def get_output_handle(self, name: str):
        names = self.get_output_names()
        if name not in names:
            raise KeyError(f"unknown output name {name!r}; available after "
                           f"run(): {names}")
        idx = names.index(name)
        pred = self

        class _Handle:
            def copy_to_cpu(self):
                return np.asarray(pred._outputs[idx])

        return _Handle()


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
