"""ONNX export (parity surface: python/paddle/onnx/export.py).

On TPU the portable artifact is StableHLO, not ONNX: ``paddle_tpu.jit.save``
exports a serialized multi-platform StableHLO program + weights that any
PJRT runtime (or MLIR toolchain) consumes — strictly more faithful to the
compiled program than an ONNX graph re-translation. ``export`` keeps the
reference's entry-point name and produces that artifact, raising only if a
literal .onnx file is demanded.
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version=None, **kwargs):
    """Export ``layer`` as a StableHLO artifact at ``path`` (the TPU-native
    interchange format). See paddle_tpu.jit.save for the file layout."""
    if path.endswith(".onnx"):
        raise NotImplementedError(
            "ONNX graph translation is not provided: the TPU-native "
            "interchange format is StableHLO (paddle_tpu.jit.save / "
            "TranslatedLayer.mlir_module). Pass a path without the .onnx "
            "suffix to export that artifact.")
    from ..jit.save_load import save
    return save(layer, path, input_spec=input_spec)
