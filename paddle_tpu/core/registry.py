"""Single-source-of-truth op registry.

The reference declares every op once in YAML (``paddle/phi/api/yaml/ops.yaml``)
and codegens five artifacts from it (C++ API, autograd nodes, Python bindings,
PIR defs, dist branch — SURVEY §1). In a JAX-native framework the compiler and
autodiff come for free, so the registry's remaining jobs are:

- **inventory**: one row per public op with its schema, for parity tracking;
- **reference semantics**: an optional numpy reference implementation that the
  OpTest-style contract suite (tests/op_contract) runs against, mirroring
  ``test/legacy_test/op_test.py:418``;
- **debug hooks**: the ``FLAGS_check_nan_inf`` sentinel wraps registered ops
  (parity: ``fluid/eager/nan_inf_utils.cc``);
- **sharding rules**: custom-kernel ops (Pallas) attach an SPMD rule, the
  analogue of ``phi/infermeta/spmd_rules/`` — builtin ops rely on GSPMD
  propagation instead of the reference's 42 hand-written rule files.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp

from . import flags

__all__ = ["OpInfo", "register_op", "get_op", "all_ops", "check_numerics"]


@dataclass
class OpInfo:
    name: str
    fn: Callable
    ref: Callable | None = None  # numpy reference impl for contract tests
    grad_ref: bool = True  # whether jax.grad should be contract-tested
    category: str = "math"
    notes: str = ""
    # contract-test hints
    test_shapes: tuple = ()
    test_dtypes: tuple = ("float32",)
    # richer contract hooks (OpTest parity, op_test.py:418):
    # make_inputs(rng) -> tuple of positional inputs for fn_call and ref;
    # fn_call defaults to fn — use it to pin keyword arguments so fn_call
    # and ref share one positional signature.
    make_inputs: Callable | None = None
    fn_call: Callable | None = None
    extra: dict = field(default_factory=dict)


_OPS: dict[str, OpInfo] = {}


def check_numerics(name: str, *outs):
    """NaN/Inf sentinel applied to op outputs when FLAGS_check_nan_inf is set."""
    for i, o in enumerate(outs):
        if isinstance(o, jax.Array) and jnp.issubdtype(o.dtype, jnp.floating):
            try:
                bad = bool(jnp.any(~jnp.isfinite(o)))
            except jax.errors.TracerBoolConversionError:
                # Inside jit: use debug callback instead of an eager check.
                jax.debug.callback(_report_nonfinite, name, i, jnp.any(~jnp.isfinite(o)))
                continue
            if bad:
                _report_nonfinite(name, i, True)


def _report_nonfinite(name, idx, bad):
    if bad:
        msg = f"[check_nan_inf] op {name!r} output #{idx} contains NaN/Inf"
        if flags.get_flag("check_nan_inf_level") > 0:
            print("WARNING:", msg)
        else:
            raise FloatingPointError(msg)


def register_op(
    name: str,
    *,
    ref: Callable | None = None,
    category: str = "math",
    grad_ref: bool = True,
    test_shapes: tuple = (),
    test_dtypes: tuple = ("float32",),
    notes: str = "",
    **extra: Any,
):
    """Decorator registering a public op.

    The wrapped function is returned unchanged except for an optional
    NaN/Inf check (active when FLAGS_check_nan_inf is on, zero cost otherwise).
    """

    def deco(fn: Callable) -> Callable:
        info = OpInfo(
            name=name, fn=fn, ref=ref, grad_ref=grad_ref, category=category,
            test_shapes=test_shapes, test_dtypes=test_dtypes, notes=notes, extra=extra,
        )
        _OPS[name] = info

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            out = fn(*args, **kwargs)
            if flags.get_flag("check_nan_inf"):
                outs = out if isinstance(out, (tuple, list)) else (out,)
                check_numerics(name, *outs)
            return out

        wrapper.__op_info__ = info
        info.fn = fn
        return wrapper

    return deco


def register_contract(
    name: str,
    fn: Callable,
    ref: Callable | None,
    make_inputs: Callable | None = None,
    *,
    fn_call: Callable | None = None,
    grad_ref: bool = False,
    category: str = "contract",
    test_dtypes: tuple = ("float32",),
    notes: str = "",
):
    """Non-decorator registration for an already-defined public op.

    This is how the blanket contract manifest (``ops/contracts.py``) enrolls
    the whole op surface: one row per op, a numpy reference with the same
    positional signature as ``fn_call``, and an input generator. The contract
    suite (tests/test_op_contract.py) enumerates every row — the analogue of
    one OpTest subclass per op in test/legacy_test/."""
    if name in _OPS and _OPS[name].ref is not None:
        return _OPS[name]  # decorator registration already carries a ref
    info = OpInfo(name=name, fn=fn, ref=ref, grad_ref=grad_ref,
                  category=category, test_dtypes=test_dtypes,
                  make_inputs=make_inputs, fn_call=fn_call or fn, notes=notes)
    _OPS[name] = info
    return info


def get_op(name: str) -> OpInfo:
    return _OPS[name]


def all_ops() -> dict[str, OpInfo]:
    return dict(_OPS)
