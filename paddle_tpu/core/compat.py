"""Version-compatibility shims for the jax API surface.

The codebase targets the modern spelling ``jax.shard_map(...,
check_vma=..., axis_names=...)``; older jax releases only ship
``jax.experimental.shard_map.shard_map`` where the kwarg is ``check_rep``
and manual axes are implied by the mesh + specs (no ``axis_names``).
Importing from here instead of from ``jax`` keeps every shard_map entry
point working on both — without it the whole ``paddle_tpu.distributed``
package fails to import on a legacy jax, taking the checkpoint/elastic
fault path down with it.
"""

from __future__ import annotations

import functools

try:
    from jax import shard_map as _shard_map  # modern jax
    _LEGACY = False
except ImportError:  # pragma: no cover — depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _LEGACY = True

__all__ = ["shard_map", "get_abstract_mesh", "get_concrete_mesh",
           "set_mesh"]


def shard_map(f=None, /, **kw):
    if f is None:
        return functools.partial(shard_map, **kw)
    if _LEGACY:
        kw.pop("axis_names", None)
        if "check_vma" in kw:
            kw["check_rep"] = bool(kw.pop("check_vma"))
    return _shard_map(f, **kw)


def get_abstract_mesh():
    """Ambient abstract mesh, or None when there is none.

    Modern jax: ``jax.sharding.get_abstract_mesh()`` (always an
    AbstractMesh, possibly ``.empty``). 0.4.x: only the internal
    ``jax._src.mesh.get_abstract_mesh`` exists and its unset default is an
    empty tuple — normalize both shapes to "mesh or None"."""
    import jax

    try:
        am = jax.sharding.get_abstract_mesh()
    except AttributeError:
        from jax._src.mesh import get_abstract_mesh as _gam
        am = _gam()
    if am is None or not hasattr(am, "empty") or am.empty:
        return None
    return am


def get_concrete_mesh():
    """Ambient concrete mesh, or None — never raises (the modern
    ``jax.sharding.get_mesh`` raises ValueError while tracing under jit,
    where no concrete mesh exists on the trace context)."""
    import jax

    try:
        get = jax.sharding.get_mesh
    except AttributeError:
        from jax._src.mesh import get_concrete_mesh as get
    try:
        m = get()
    except ValueError:
        return None
    return m if isinstance(m, jax.sharding.Mesh) and not m.empty else None


def set_mesh(mesh):
    """Ambient-mesh context: ``jax.sharding.set_mesh`` where it exists.
    On 0.4.x only internals exist, and the internal ``set_mesh`` also flips
    the experimental ``sharding_in_types`` config — which that release
    can't actually trace through (tracers have no ``.sharding``) — so
    install just the abstract + concrete ambient mesh contexts."""
    import contextlib

    import jax

    try:
        return jax.sharding.set_mesh(mesh)
    except AttributeError:
        pass

    @contextlib.contextmanager
    def _legacy():
        from jax._src.mesh import set_abstract_mesh, set_concrete_mesh
        with set_abstract_mesh(mesh.abstract_mesh), set_concrete_mesh(mesh):
            yield mesh

    return _legacy()
