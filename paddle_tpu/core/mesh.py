"""Device and mesh abstraction.

Replaces the reference's Place/DeviceContext machinery
(``paddle/phi/common/place.h``, ``paddle/phi/backends/gpu/gpu_context.h:84``)
and the fleet 5-axis topology (``fleet/base/topology.py:66`` axes
[data, pipe, sharding, sep, model]) with jax devices + ``jax.sharding.Mesh``.

XLA owns streams/allocators on TPU; what remains framework-level is (a) device
listing/selection, (b) a process-global current mesh with the canonical hybrid
axes, and (c) per-axis group info (rank/size) mirroring HybridCommunicateGroup.
"""

from __future__ import annotations

import contextlib
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "get_device", "set_device", "device_count", "is_compiled_with_tpu",
    "HYBRID_AXES", "make_mesh", "current_mesh", "use_mesh", "axis_size",
    "HybridTopology",
]

P = PartitionSpec

# Canonical hybrid-parallel axes, matching the reference's 5-D topology
# (fleet/base/topology.py:66-69): data, pipe, sharding(fsdp), sep(sequence), model(tp).
HYBRID_AXES = ("dp", "pp", "fsdp", "sep", "mp")

_current_mesh: list[Mesh | None] = [None]
_current_device: list[jax.Device | None] = [None]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def get_device() -> jax.Device:
    return _current_device[0] or jax.devices()[0]


def set_device(device: str | jax.Device) -> jax.Device:
    """Accepts 'tpu:0' / 'cpu:1' style strings (parity: paddle.set_device)."""
    if isinstance(device, str):
        if ":" in device:
            platform, idx = device.split(":")
            device = jax.devices(platform)[int(idx)]
        else:
            device = jax.devices(device)[0]
    _current_device[0] = device
    return device


def make_mesh(
    axis_sizes: Sequence[int] | dict[str, int],
    axis_names: Sequence[str] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh. ``make_mesh({'dp':2,'mp':4})`` or ``make_mesh((2,4), ('dp','mp'))``.

    Axis order follows the convention: outermost axes map across hosts/DCN,
    innermost across ICI — put 'mp'/'sep' innermost for bandwidth-hungry
    collectives (the declarative analogue of the reference's ordered
    CommunicateTopology axes).
    """
    if isinstance(axis_sizes, dict):
        axis_names = tuple(axis_sizes.keys())
        sizes = tuple(axis_sizes.values())
    else:
        sizes = tuple(axis_sizes)
        if axis_names is None:
            axis_names = HYBRID_AXES[: len(sizes)]
    devs = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(sizes))
    if n > len(devs):
        raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
    arr = np.array(devs[:n]).reshape(sizes)
    return Mesh(arr, tuple(axis_names))


def current_mesh() -> "Mesh | jax.sharding.AbstractMesh | None":
    """Active mesh: this library's use_mesh stack, else the ambient jax
    mesh. While tracing under an ambient ``set_mesh`` scope the return is
    an AbstractMesh (no concrete mesh exists on the trace context) —
    callers may rely on ``.shape``/``.axis_names`` and shard_map, not on
    ``.devices`` or ``with mesh:``."""
    if _current_mesh[0] is not None:
        return _current_mesh[0]
    # fall back to the ambient jax mesh so callers that gate on an active
    # mesh (e.g. MoE sorted-dispatch fallback) see meshes activated without
    # this library's use_mesh wrapper: the modern jax.sharding.set_mesh
    # context first, then the legacy `with mesh:` thread resources (private
    # import — the public pxla alias is deprecated; guarded so removal just
    # disables the legacy bridge, never the set_mesh path). Both ambient
    # getters go through core.compat, which papers over jax releases where
    # jax.sharding.{get_abstract_mesh,get_mesh} don't exist yet.
    from .compat import get_abstract_mesh, get_concrete_mesh
    am = get_abstract_mesh()
    if am is not None:
        # while tracing under jit there is no concrete mesh on the trace
        # context; callers only inspect .shape/.axis_names or feed
        # shard_map, all of which accept the abstract mesh
        return get_concrete_mesh() or am
    try:
        from jax._src.mesh import thread_resources
        pm = thread_resources.env.physical_mesh
        if not pm.empty:
            return pm
    except Exception:
        pass
    return None


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    prev = _current_mesh[0]
    _current_mesh[0] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _current_mesh[0] = prev


def axis_size(name: str, mesh: Mesh | None = None) -> int:
    mesh = mesh or current_mesh()
    if mesh is None or name not in mesh.axis_names:
        return 1
    return mesh.shape[name]


class HybridTopology:
    """Per-axis rank/size bookkeeping over a Mesh.

    Parity: ``HybridCommunicateGroup`` (fleet/base/topology.py:178) — but
    declarative: groups are mesh axes, collectives are compiled by XLA, so no
    communicator objects are created here.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def get_parallel_degree(self, axis: str) -> int:
        return axis_size(axis, self.mesh)

    @property
    def dp_degree(self):
        return self.get_parallel_degree("dp")

    @property
    def mp_degree(self):
        return self.get_parallel_degree("mp")

    @property
    def pp_degree(self):
        return self.get_parallel_degree("pp")

    @property
    def sharding_degree(self):
        return self.get_parallel_degree("fsdp")

    @property
    def sep_degree(self):
        return self.get_parallel_degree("sep")

    def named_sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))
