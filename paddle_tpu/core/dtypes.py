"""Dtype system and promotion.

Equivalent of the reference's ``phi::DataType`` (``paddle/phi/common/data_type.h``)
and the dtype-promotion logic in ``python/paddle/framework/dtype.py``. On TPU we
standardize on jax/numpy dtypes; bfloat16 is the preferred reduced precision
(MXU-native) rather than the reference's fp16-first GPU stance.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any

import jax.numpy as jnp
import numpy as np

__all__ = [
    "float16", "bfloat16", "float32", "float64",
    "int8", "int16", "int32", "int64",
    "uint8", "bool_", "complex64", "complex128",
    "float8_e4m3fn", "float8_e5m2",
    "get_default_dtype", "set_default_dtype", "promote_types",
    "is_floating_point", "is_integer", "is_complex", "canonical_dtype",
    "finfo", "iinfo",
]

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "bool": bool_,
    "complex64": complex64, "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn, "float8_e5m2": float8_e5m2,
}

_default_dtype = [jnp.float32]


def canonical_dtype(dtype: Any):
    """Map str/np/jnp dtype spec to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _ALIASES:
            raise ValueError(f"Unknown dtype string {dtype!r}")
        return _ALIASES[dtype]
    return jnp.dtype(dtype).type


def get_default_dtype():
    return _default_dtype[0]


def set_default_dtype(dtype: Any) -> None:
    d = canonical_dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating):
        raise ValueError("default dtype must be floating point")
    _default_dtype[0] = d


@contextlib.contextmanager
def default_dtype_guard(dtype: Any):
    """Temporarily set the default floating dtype (parity:
    paddle.set_default_dtype scoping used by model constructors — the
    reference's Layer picks up paddle.get_default_dtype() at parameter
    creation, python/paddle/nn/layer/layers.py). Model configs with
    ``dtype="bfloat16"`` wrap construction in this guard so every sublayer
    (Linear/Embedding/LayerNorm) creates its parameters in that dtype."""
    prev = _default_dtype[0]
    set_default_dtype(dtype)
    try:
        yield
    finally:
        _default_dtype[0] = prev


def scoped_dtype_init(init):
    """Decorator for model ``__init__(self, config, ...)``: construction runs
    under ``default_dtype_guard(config.dtype)`` so every sublayer creates its
    parameters in the config's dtype (a bf16 config really builds a bf16
    model — VERDICT r3: the round-3 benches silently ran fp32 storage)."""
    @functools.wraps(init)
    def wrapped(self, config, *args, **kwargs):
        with default_dtype_guard(getattr(config, "dtype", None)
                                 or get_default_dtype()):
            return init(self, config, *args, **kwargs)
    return wrapped


def promote_types(a: Any, b: Any):
    """Binary dtype promotion (jax lattice; matches paddle's T+T rules for the
    common cases: int+float -> float, f16+f32 -> f32, bf16+f16 -> f32)."""
    return jnp.promote_types(canonical_dtype(a), canonical_dtype(b))


def is_floating_point(x: Any) -> bool:
    d = getattr(x, "dtype", x)
    return jnp.issubdtype(jnp.dtype(d), jnp.floating)


def is_integer(x: Any) -> bool:
    d = getattr(x, "dtype", x)
    return jnp.issubdtype(jnp.dtype(d), jnp.integer)


def is_complex(x: Any) -> bool:
    d = getattr(x, "dtype", x)
    return jnp.issubdtype(jnp.dtype(d), jnp.complexfloating)


def finfo(dtype):
    return jnp.finfo(canonical_dtype(dtype))


def iinfo(dtype):
    return np.iinfo(jnp.dtype(canonical_dtype(dtype)))
