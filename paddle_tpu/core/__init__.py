"""Core layer: flags, dtypes, errors, rng, mesh, op registry.

TPU-native stand-in for the reference's paddle/common + phi/core foundations
(SURVEY §2.1/§2.2): XLA owns allocation/streams/layout, so what remains is
configuration, dtype semantics, RNG streams, device/mesh handles, and the
single-source op registry.
"""

from . import dtypes, errors, flags, mesh, registry, rng
from .errors import EnforceNotMet, enforce
from .flags import get_flags, set_flags
from .mesh import (
    HYBRID_AXES,
    HybridTopology,
    axis_size,
    current_mesh,
    device_count,
    get_device,
    is_compiled_with_tpu,
    make_mesh,
    set_device,
    use_mesh,
)
from .registry import all_ops, get_op, register_op
from .rng import RNGStatesTracker, get_tracker, next_key, rng_stream, seed
