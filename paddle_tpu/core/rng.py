"""Random number state management.

Replaces the reference's per-device generator state
(``paddle/phi/core/generator.cc``) and the tensor-parallel RNG state tracker
(``python/paddle/distributed/fleet/layers/mpu/random.py``) with JAX threefry
key streams:

- Eager mode: a process-global key advanced per draw (paddle's ``paddle.seed``).
- Traced (jit) mode: a context-scoped stream seeded from a key passed into
  ``functional_call``; draws are derived deterministically by fold_in with a
  Python-side counter, so retraces are reproducible and jit stays pure.
- Named streams (``RNGStatesTracker``): independent sub-streams, e.g.
  "global" vs "local" dropout seeds under tensor parallelism so replicated
  activations drop identically while model-parallel-private activations
  drop independently.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator

import jax

__all__ = ["seed", "next_key", "rng_stream", "RNGStatesTracker", "get_tracker", "default_key"]

_state = threading.local()


def _global():
    if not hasattr(_state, "key"):
        # the lazy init may first be reached INSIDE a jit/eval_shape trace
        # (e.g. a thread's first draw happens under a transform);
        # ensure_compile_time_eval keeps the stored key a concrete array —
        # storing a tracer here would poison every later eager draw with
        # an escaped-tracer error
        with jax.ensure_compile_time_eval():
            _state.key = jax.random.key(0)
        _state.stack = []
    return _state


def seed(value: int) -> None:
    """Seed the process-global eager RNG (parity: ``paddle.seed``)."""
    s = _global()
    with jax.ensure_compile_time_eval():
        s.key = jax.random.key(int(value))


def default_key() -> jax.Array:
    return _global().key


class _Stream:
    """A deterministic key stream: key_i = fold_in(base, i)."""

    def __init__(self, base_key: jax.Array):
        self.base = base_key
        self.counter = 0

    def next(self) -> jax.Array:
        k = jax.random.fold_in(self.base, self.counter)
        self.counter += 1
        return k


@contextlib.contextmanager
def rng_stream(base_key: jax.Array) -> Iterator[_Stream]:
    """Scope a deterministic key stream; ``next_key()`` draws from it.

    Used by ``nn.functional_call`` so stochastic layers (dropout) are pure
    under jit: the caller supplies one key, layers draw derived keys in
    deterministic call order.
    """
    s = _global()
    stream = _Stream(base_key)
    s.stack.append(stream)
    try:
        yield stream
    finally:
        s.stack.pop()


def next_key() -> jax.Array:
    """Draw the next RNG key: from the innermost scoped stream if one is
    active (pure/traced mode) else by advancing the global eager key.

    The eager advance runs under ``ensure_compile_time_eval``: if a layer
    draws from the global stream while being traced (no functional_call
    stream scoped), the split happens eagerly and the stored key stays
    concrete — the traced program bakes the drawn key in as a constant
    (one pattern per compilation) instead of poisoning the global state
    with an escaped tracer. Pass ``rngs`` to functional_call for
    per-call randomness under jit."""
    s = _global()
    if s.stack:
        return s.stack[-1].next()
    with jax.ensure_compile_time_eval():
        s.key, sub = jax.random.split(s.key)
    return sub


class RNGStatesTracker:
    """Named independent RNG streams (parity: fleet mpu/random.py:RNGStatesTracker).

    Under tensor parallelism, dropout on replicated tensors must use the same
    seed on every model-parallel rank while dropout on partitioned tensors
    must use different seeds; each case gets its own named stream.
    """

    def __init__(self):
        self.streams: dict[str, _Stream] = {}

    def add(self, name: str, seed_value: int) -> None:
        if name in self.streams:
            raise ValueError(f"RNG stream {name!r} already exists")
        self.streams[name] = _Stream(jax.random.key(seed_value))

    @contextlib.contextmanager
    def stream(self, name: str):
        if name not in self.streams:
            raise ValueError(f"Unknown RNG stream {name!r}; call add() first")
        s = _global()
        s.stack.append(self.streams[name])
        try:
            yield
        finally:
            s.stack.pop()


_TRACKER = RNGStatesTracker()


def get_tracker() -> RNGStatesTracker:
    return _TRACKER
