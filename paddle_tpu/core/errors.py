"""Rich error raising utilities.

Equivalent of the reference's ``PADDLE_ENFORCE`` machinery
(``paddle/common/enforce.h`` / ``paddle/phi/core/enforce.h``): check a
condition and raise a typed, well-formatted error carrying context. On TPU
there is no CUDA error table to enrich; instead we attach the op name and
argument summaries when raised through the op registry.
"""

from __future__ import annotations

from typing import Any, NoReturn

__all__ = ["EnforceNotMet", "enforce", "enforce_eq", "enforce_shape_match", "raise_error"]


class EnforceNotMet(RuntimeError):
    """Error raised when an enforce check fails (parity: paddle EnforceNotMet)."""

    def __init__(self, message: str, hint: str = ""):
        self.hint = hint
        full = message if not hint else f"{message}\n  [Hint: {hint}]"
        super().__init__(full)


def _summ(v: Any) -> str:
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None:
        return f"Tensor(shape={tuple(shape)}, dtype={dtype})"
    return repr(v)


def enforce(cond: bool, message: str, hint: str = "") -> None:
    if not cond:
        raise EnforceNotMet(message, hint)


def enforce_eq(a: Any, b: Any, message: str = "") -> None:
    if a != b:
        raise EnforceNotMet(message or f"Expected equality, got {a!r} != {b!r}")


def enforce_shape_match(x: Any, expected: tuple, name: str = "input") -> None:
    shape = tuple(getattr(x, "shape", ()))
    if len(shape) != len(expected) or any(
        e is not None and e != s for s, e in zip(shape, expected)
    ):
        raise EnforceNotMet(
            f"Shape mismatch for {name}: got {shape}, expected {expected} (None = any)."
        )


def raise_error(message: str, *args: Any) -> NoReturn:
    detail = ", ".join(_summ(a) for a in args)
    raise EnforceNotMet(message + (f" [args: {detail}]" if detail else ""))
