"""Global flag registry.

TPU-native equivalent of the reference's ``PD_DEFINE_*`` flag system
(``paddle/common/flags.h:38``, exported map ``paddle/common/flags.cc:20``):
a process-global registry of typed flags, overridable from the environment
(``FLAGS_<name>``) and from Python via :func:`set_flags` / :func:`get_flags`,
mirroring ``paddle.set_flags``.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable

__all__ = [
    "define_flag",
    "get_flags",
    "set_flags",
    "flag_guard",
]


@dataclass
class _FlagInfo:
    name: str
    default: Any
    value: Any
    doc: str
    type: type


_REGISTRY: dict[str, _FlagInfo] = {}
_LOCK = threading.RLock()


def _coerce(raw: str, ty: type) -> Any:
    if ty is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return ty(raw)


def define_flag(name: str, default: Any, doc: str = "") -> None:
    """Register a flag. Environment variable ``FLAGS_<name>`` overrides the default."""
    with _LOCK:
        if name in _REGISTRY:
            return
        ty = type(default)
        value = default
        env = os.environ.get(f"FLAGS_{name}")
        if env is not None:
            value = _coerce(env, ty)
        _REGISTRY[name] = _FlagInfo(name, default, value, doc, ty)


def get_flags(names: str | list[str] | None = None) -> dict[str, Any]:
    with _LOCK:
        if names is None:
            return {k: v.value for k, v in _REGISTRY.items()}
        if isinstance(names, str):
            names = [names]
        out = {}
        for n in names:
            if n not in _REGISTRY:
                raise ValueError(f"Unknown flag: {n!r}")
            out[n] = _REGISTRY[n].value
        return out


def get_flag(name: str) -> Any:
    return get_flags([name])[name]


def set_flags(flags: dict[str, Any]) -> None:
    with _LOCK:
        for name, value in flags.items():
            if name not in _REGISTRY:
                raise ValueError(f"Unknown flag: {name!r}")
            info = _REGISTRY[name]
            info.value = _coerce(value, info.type) if isinstance(value, str) else info.type(value)


class flag_guard:
    """Context manager to temporarily override flags."""

    def __init__(self, **flags: Any):
        self._new = flags
        self._old: dict[str, Any] = {}

    def __enter__(self):
        self._old = get_flags(list(self._new))
        set_flags(self._new)
        return self

    def __exit__(self, *exc):
        set_flags(self._old)
        return False


# --- Core flags (parity with the reference's most used FLAGS_*) ---
define_flag("check_nan_inf", False, "Check every registered op output for NaN/Inf.")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0: warn only.")
define_flag("default_dtype", "float32", "Default floating point dtype.")
define_flag("enable_x64", False, "Allow 64-bit dtypes (maps to jax_enable_x64).")
define_flag("benchmark", False, "Synchronize after each op for timing.")
define_flag("matmul_precision", "default", "XLA matmul precision: default|high|highest.")
define_flag("log_level", 1, "VLOG-style verbosity for paddle_tpu logging.")
define_flag("flash_block_q", 1024, "Flash attention q-block rows (read at "
            "TRACE time: set before the first jit of a shape, or sweep in "
            "separate processes).")
define_flag("flash_block_k", 1024, "Flash attention k-block cols (trace-time,"
            " see flash_block_q).")
define_flag("flash_min_seq", 256, "Minimum q sequence length for routing "
            "scaled_dot_product_attention onto the Pallas flash kernel on "
            "TPU (below it the XLA bf16 path wins on launch overhead).")
define_flag("flash_batch_axes", "dp",
            "Comma-separated mesh axis names the flash SPMD rule shards the "
            "BATCH dim over when the arrays' own sharding is unavailable "
            "(jit tracing). Set for meshes with non-canonical axis names.")
define_flag("flash_head_axes", "mp",
            "Comma-separated mesh axis names the flash SPMD rule shards the "
            "HEADS dim over (see flash_batch_axes).")
define_flag("comm_watchdog_timeout", 300.0,
            "Seconds before the comm watchdog flags a blocking comm/sync "
            "call as hung (parity: FLAGS_enable_async_trace timeout).")
