"""DataLoader with samplers, worker threads, and device prefetch
(parity: python/paddle/io/reader.py:216 DataLoader +
io/dataloader/{batch_sampler,dataloader_iter,worker}.py).

The reference forks worker *processes* with shared-memory transport because
CPython + CUDA favor process isolation. Here workers are threads (numpy
releases the GIL for the heavy copies) feeding a bounded queue, plus an
optional device-prefetch stage that issues jax.device_put one batch ahead —
the piece that actually hides H2D latency on TPU.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .dataset import Dataset, IterableDataset

__all__ = ["Sampler", "SequenceSampler", "RandomSampler", "SubsetRandomSampler",
           "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
           "DataLoader", "default_collate_fn"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng(self.generator)
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__(None)
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        rng = np.random.default_rng(self.generator)
        return iter(np.array(self.indices)[rng.permutation(len(self.indices))].tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        return iter(rng.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        super().__init__(dataset)
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (parity:
    io/dataloader/batch_sampler.py DistributedBatchSampler). On a single-host
    GSPMD setup prefer feeding the global batch and sharding via the mesh; this
    sampler exists for multi-process (jax.distributed) loops."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        import jax
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else jax.process_count()
        self.local_rank = rank if rank is not None else jax.process_index()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank: self.total_size: self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch: Sequence[Any]):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if hasattr(sample, "__array__"):
        return np.stack([np.asarray(b) for b in batch])
    return batch


class _Prefetcher:
    """Background thread filling a bounded queue."""

    _DONE = object()

    def __init__(self, gen_fn: Callable[[], Iterable], depth: int):
        self.q: queue.Queue = queue.Queue(maxsize=depth)
        self.gen_fn = gen_fn
        self.err = None
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        try:
            for item in self.gen_fn():
                self.q.put(item)
        except BaseException as e:  # propagate to consumer
            self.err = e
        finally:
            self.q.put(self._DONE)

    def __iter__(self):
        while True:
            item = self.q.get()
            if item is self._DONE:
                if self.err is not None:
                    raise self.err
                return
            yield item


def _mp_worker_loop(dataset, collate_fn, task_q, result_q, use_shm,
                    worker_init_fn, worker_id):
    """Worker process: fetch + collate batches; ship arrays back through
    POSIX shared memory (parity: the reference's multiprocess workers with
    shared-memory tensor transport, io/reader.py:216 + dataloader/worker.py)."""
    from multiprocessing import shared_memory
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        task = task_q.get()
        if task is None:
            return
        eid, bid, idxs = task
        try:
            batch = collate_fn([dataset[i] for i in idxs])
            if use_shm:
                def pack(a):
                    if isinstance(a, np.ndarray) and a.nbytes > 0:
                        shm = shared_memory.SharedMemory(create=True,
                                                         size=a.nbytes)
                        np.ndarray(a.shape, a.dtype, buffer=shm.buf)[...] = a
                        name = shm.name
                        shm.close()
                        return ("__shm__", name, a.shape, str(a.dtype))
                    return a
                batch = [pack(b) for b in batch] if isinstance(batch, list) \
                    else pack(batch)
            result_q.put((eid, bid, batch, None))
        except BaseException as e:  # noqa: BLE001 - ship to parent
            result_q.put((eid, bid, None, e))


class _MPWorkers:
    """Persistent multiprocess fetch pool with in-order delivery."""

    def __init__(self, dataset, collate_fn, num_workers, use_shared_memory,
                 worker_init_fn):
        import multiprocessing as mp
        import pickle
        # fork is unsafe once JAX's internal threads exist (deadlocks the
        # child); forkserver forks from a clean helper process instead,
        # spawn is the portable fallback. Dataset/collate_fn must pickle —
        # same contract as the reference's spawn-mode DataLoader; check up
        # front so the error names the offender instead of a PicklingError
        # from deep inside Process.start().
        for name, obj in (("dataset", dataset), ("collate_fn", collate_fn),
                          ("worker_init_fn", worker_init_fn)):
            try:
                # stream to devnull: validates without materializing a
                # second copy of a large in-memory dataset
                with open(os.devnull, "wb") as sink:
                    pickle.Pickler(sink).dump(obj)
            except Exception as e:  # noqa: BLE001
                raise TypeError(
                    f"num_workers>0 sends {name} to worker processes via "
                    f"forkserver/spawn, which requires it to be picklable "
                    f"(module-level functions/classes, no lambdas or "
                    f"closures): {e}") from e
        methods = mp.get_all_start_methods()
        ctx = mp.get_context(
            "forkserver" if "forkserver" in methods else "spawn")
        self.task_q = ctx.Queue()
        self.result_q = ctx.Queue()
        self.use_shm = use_shared_memory
        self.epoch = 0
        self.procs = [
            ctx.Process(target=_mp_worker_loop,
                        args=(dataset, collate_fn, self.task_q,
                              self.result_q, use_shared_memory,
                              worker_init_fn, i), daemon=True)
            for i in range(num_workers)]
        for p in self.procs:
            p.start()

    def _unpack(self, batch):
        from multiprocessing import shared_memory

        def un(a):
            if isinstance(a, tuple) and len(a) == 4 and a[0] == "__shm__":
                _, name, shape, dtype = a
                shm = shared_memory.SharedMemory(name=name)
                arr = np.array(np.ndarray(shape, dtype, buffer=shm.buf),
                               copy=True)
                shm.close()
                shm.unlink()
                return arr
            return a
        return [un(b) for b in batch] if isinstance(batch, list) else un(batch)

    def _discard(self, batch):
        """Unlink shm segments of a batch that will never be consumed."""
        from multiprocessing import shared_memory
        items = batch if isinstance(batch, list) else [batch]
        for a in items:
            if isinstance(a, tuple) and len(a) == 4 and a[0] == "__shm__":
                try:
                    shm = shared_memory.SharedMemory(name=a[1])
                    shm.close()
                    shm.unlink()
                except FileNotFoundError:
                    pass

    def run_epoch(self, index_batches):
        # epoch ids isolate reused pools from a partially-consumed previous
        # epoch: stale results are drained (and their shm unlinked) instead
        # of being served as this epoch's data
        self.epoch += 1
        epoch = self.epoch
        n = 0
        for bid, idxs in enumerate(index_batches):
            self.task_q.put((epoch, bid, list(idxs)))
            n += 1
        pending = {}
        want = 0
        try:
            while want < n:
                if want in pending:
                    batch, err = pending.pop(want)
                else:
                    eid, bid, batch, err = self.result_q.get()
                    if eid != epoch:  # stale from an abandoned epoch
                        if err is None:
                            self._discard(batch)
                        continue
                    if bid != want:
                        pending[bid] = (batch, err)
                        continue
                if err is not None:
                    raise err
                yield self._unpack(batch)
                want += 1
        finally:
            for batch, err in pending.values():
                if err is None:
                    self._discard(batch)

    def shutdown(self):
        for _ in self.procs:
            try:
                self.task_q.put(None)
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.shutdown()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2, use_shared_memory=True,
                 timeout=0, worker_init_fn=None, to_device=True):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(1, prefetch_factor)
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.to_device = to_device
        self._mp_pool = None
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def _raw_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.num_workers > 0:
            # multiprocess fetch + shared-memory transport (parity:
            # io/reader.py:216 multiprocess DataLoader)
            if self._mp_pool is None:
                self._mp_pool = _MPWorkers(self.dataset, self.collate_fn,
                                           self.num_workers,
                                           self.use_shared_memory,
                                           self.worker_init_fn)
            yield from self._mp_pool.run_epoch(list(self.batch_sampler))
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _device_batches(self):
        import jax
        src = self._raw_batches()
        if not self.to_device:
            yield from src
            return
        for batch in src:
            yield jax.tree.map(
                lambda a: jax.device_put(np.asarray(a)) if isinstance(
                    a, (np.ndarray, np.number)) else a, batch,
                is_leaf=lambda a: isinstance(a, (np.ndarray, np.number)))

    def __iter__(self):
        if self.use_buffer_reader:
            depth = self.prefetch_factor * max(1, self.num_workers)
            return iter(_Prefetcher(self._device_batches, depth))
        return self._device_batches()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)
