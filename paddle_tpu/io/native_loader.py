"""Native (C++) input-pipeline fast path (parity: the reference's C++ data
machinery — DataFeed/MultiSlotDataFeed multi-threaded readers
``fluid/framework/data_feed.h:1134`` and the C++ side of the DataLoader;
SURVEY §7 "C++ data-loading fast path").

Two memcpy-bound hot loops live in C++ (built on first use with the host
toolchain via utils.cpp_extension, dlopened with ctypes):

- ``pack_sequences``: greedy first-fit packing of variable-length token
  sequences into fixed-length rows, emitting cu_seqlens for the varlen
  flash kernel (the packed-pretraining input format);
- ``gather_rows``: threaded gather of sample rows from a flat token
  corpus into a batch buffer (the shuffle-read inner loop).

Pure-numpy fallbacks keep the API working where no compiler exists; the
``native`` flag reports which path is active.
"""

from __future__ import annotations

import ctypes

import numpy as np

__all__ = ["pack_sequences", "gather_rows", "native_available"]

_SRC = r"""
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Greedy sequential packing: walk sequences in order, start a new row when
// the current one cannot fit the next sequence (or row seq budget is hit).
// lengths[n] -> rows of width row_len filled with concatenated sequences,
// padded with pad_id. Emits per-row segment starts (cu_seqlens layout:
// row-major, -1 terminated). Returns number of rows produced.
int64_t pack_sequences(const int32_t* tokens, const int64_t* offsets,
                       int64_t n_seqs, int64_t row_len, int32_t pad_id,
                       int32_t* out_rows, int64_t max_rows,
                       int64_t* out_cu, int64_t max_cu_per_row) {
  int64_t row = 0;
  int64_t col = 0;
  int64_t cu_idx = 0;
  // init first row
  for (int64_t j = 0; j < row_len; ++j) out_rows[j] = pad_id;
  for (int64_t c = 0; c < max_cu_per_row; ++c) out_cu[c] = -1;
  out_cu[0] = 0; cu_idx = 1;
  for (int64_t s = 0; s < n_seqs; ++s) {
    const int64_t len = offsets[s + 1] - offsets[s];
    if (len > row_len) continue;  // skip oversize (caller pre-truncates)
    if (col + len > row_len || cu_idx >= max_cu_per_row) {
      // close row, start next
      ++row;
      if (row >= max_rows) return -1;
      col = 0;
      cu_idx = 1;
      int32_t* r = out_rows + row * row_len;
      for (int64_t j = 0; j < row_len; ++j) r[j] = pad_id;
      int64_t* cu = out_cu + row * max_cu_per_row;
      for (int64_t c = 0; c < max_cu_per_row; ++c) cu[c] = -1;
      cu[0] = 0;
    }
    std::memcpy(out_rows + row * row_len + col, tokens + offsets[s],
                sizeof(int32_t) * len);
    col += len;
    out_cu[row * max_cu_per_row + cu_idx] = col;
    ++cu_idx;
  }
  return row + 1;
}

// Threaded gather: out[i] = corpus[idx[i]*row_len : (idx[i]+1)*row_len]
void gather_rows(const int32_t* corpus, const int64_t* idx, int64_t n,
                 int64_t row_len, int32_t* out, int64_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  auto work = [&](int64_t t) {
    for (int64_t i = t; i < n; i += n_threads) {
      std::memcpy(out + i * row_len, corpus + idx[i] * row_len,
                  sizeof(int32_t) * row_len);
    }
  };
  if (n_threads == 1) { work(0); return; }
  std::vector<std::thread> ts;
  for (int64_t t = 0; t < n_threads; ++t) ts.emplace_back(work, t);
  for (auto& th : ts) th.join();
}

}  // extern "C"
"""

_LIB = None
_TRIED = False


def _lib():
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        try:
            from ..utils.cpp_extension import load_inline
            lib = load_inline("pt_fastloader", _SRC)
            lib.pack_sequences.restype = ctypes.c_int64
            lib.pack_sequences.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
            lib.gather_rows.restype = None
            lib.gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
            _LIB = lib
        except Exception:
            _LIB = None
    return _LIB


def native_available() -> bool:
    return _lib() is not None


def _ptr(a):
    return a.ctypes.data_as(ctypes.c_void_p)


def pack_sequences(seqs, row_len: int, pad_id: int = 0,
                   max_segments_per_row: int = 64, force_numpy: bool = False):
    """Pack variable-length sequences into [rows, row_len] + per-row
    cu_seqlens (-1 padded). Returns (rows, cu)."""
    keep = [np.asarray(s[:row_len], np.int32) for s in seqs
            if 0 < len(s)]
    if not keep:
        return (np.full((0, row_len), pad_id, np.int32),
                np.full((0, max_segments_per_row), -1, np.int64))
    tokens = np.concatenate(keep).astype(np.int32)
    offsets = np.concatenate([[0], np.cumsum([len(s) for s in keep])]) \
        .astype(np.int64)
    n = len(keep)
    max_rows = n  # worst case: one row per sequence
    lib = None if force_numpy else _lib()
    if lib is not None:
        rows = np.empty((max_rows, row_len), np.int32)
        cu = np.empty((max_rows, max_segments_per_row), np.int64)
        n_rows = lib.pack_sequences(_ptr(tokens), _ptr(offsets), n, row_len,
                                    pad_id, _ptr(rows), max_rows, _ptr(cu),
                                    max_segments_per_row)
        if n_rows >= 0:
            return rows[:n_rows], cu[:n_rows]
    # numpy fallback — same greedy algorithm
    rows_l, cus, cur, cu_row = [], [], [], [0]
    col = 0
    for s in keep:
        if col + len(s) > row_len or len(cu_row) >= max_segments_per_row:
            pad = np.full(row_len - col, pad_id, np.int32)
            rows_l.append(np.concatenate(cur + [pad]) if cur else pad)
            cus.append(cu_row)
            cur, col, cu_row = [], 0, [0]
        cur.append(s)
        col += len(s)
        cu_row.append(col)
    pad = np.full(row_len - col, pad_id, np.int32)
    rows_l.append(np.concatenate(cur + [pad]) if cur else pad)
    cus.append(cu_row)
    out_cu = np.full((len(rows_l), max_segments_per_row), -1, np.int64)
    for i, c in enumerate(cus):
        out_cu[i, :len(c)] = c
    return np.stack(rows_l), out_cu


def gather_rows(corpus, idx, row_len: int, n_threads: int = 4,
                force_numpy: bool = False):
    """Gather [len(idx), row_len] token rows from a flat int32 corpus."""
    corpus = np.ascontiguousarray(corpus, np.int32).reshape(-1)
    idx = np.ascontiguousarray(idx, np.int64)
    n_rows = corpus.size // row_len
    if idx.size and (idx.min() < 0 or idx.max() >= n_rows):
        raise IndexError(f"row index out of range [0, {n_rows}) "
                         f"(got {int(idx.min())}..{int(idx.max())})")
    lib = None if force_numpy else _lib()
    if lib is not None:
        out = np.empty((len(idx), row_len), np.int32)
        lib.gather_rows(_ptr(corpus), _ptr(idx), len(idx), row_len,
                        _ptr(out), n_threads)
        return out
    c2 = corpus.reshape(-1, row_len) if corpus.size % row_len == 0 else None
    if c2 is not None:
        return c2[idx]
    return np.stack([corpus[i * row_len:(i + 1) * row_len] for i in idx])
