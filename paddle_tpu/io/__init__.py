"""paddle_tpu.io — datasets and DataLoader (parity: python/paddle/io/).

The reference DataLoader (io/reader.py:216) uses multiprocess workers with
shared-memory tensor transport feeding CUDA streams. On TPU the input
pipeline's job is to keep host batches ready ahead of device dispatch:
worker threads/processes produce numpy batches, and the loader prefetches
``device_put`` transfers so step N+1's H2D overlaps step N's compute.
"""

from .dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .dataloader import (  # noqa: F401
    BatchSampler, DataLoader, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler, default_collate_fn,
)
