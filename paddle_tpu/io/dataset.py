"""Dataset abstractions (parity: python/paddle/io/dataloader/dataset.py)."""

from __future__ import annotations

import bisect
from typing import Iterable, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [np.asarray(t) for t in tensors]
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("all tensors must share dim 0")
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError("all datasets must have the same length")

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        base = 0 if di == 0 else self.cumulative_sizes[di - 1]
        return self.datasets[di][idx - base]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) and 0 <= l <= 1 for l in lengths):
        sizes = [int(np.floor(total * l)) for l in lengths]
        for i in range(total - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    rng = np.random.default_rng(None if generator is None else generator)
    perm = rng.permutation(total)
    out, start = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[start:start + l].tolist()))
        start += l
    return out
