"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's
capabilities, built on jax/XLA/Pallas (capability rebuild, not a port; see
SURVEY.md for the reference structural map).

Public surface mirrors `import paddle`: tensor ops at top level, `nn`,
`optimizer`, `io`, `amp`, `jit`, `distributed` (as `parallel`), `vision`,
plus framework-level save/load, seed, device and flag control.
"""

__version__ = "0.1.0"

from . import core
from .core import (  # noqa: F401
    EnforceNotMet,
    enforce,
    get_flags,
    set_flags,
    seed,
)
from .core.dtypes import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    float8_e4m3fn, float8_e5m2, get_default_dtype, int8, int16, int32, int64,
    promote_types, set_default_dtype, uint8, finfo, iinfo,
)
from .core.mesh import (  # noqa: F401
    device_count,
    get_device,
    is_compiled_with_tpu,
    make_mesh,
    set_device,
    use_mesh,
)
from .ops import *  # noqa: F401,F403
from .ops.creation import Tensor  # noqa: F401

from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import io  # noqa: E402
from . import amp  # noqa: E402
from . import jit  # noqa: E402
from . import framework  # noqa: E402
from .framework.io import load, save  # noqa: E402
from . import metric  # noqa: E402
from . import vision  # noqa: E402
from . import distributed  # noqa: E402
from . import profiler  # noqa: E402
from . import incubate  # noqa: E402
from . import inference  # noqa: E402
from . import hapi  # noqa: E402
from .hapi.flops import flops, summary  # noqa: E402
from . import distribution  # noqa: E402
from . import fft  # noqa: E402
from . import signal  # noqa: E402
from . import utils  # noqa: E402
from . import autograd  # noqa: E402
from .autograd import no_grad  # noqa: E402  (paddle.no_grad parity)
from .nn.initializer import LazyGuard  # noqa: E402  (paddle.LazyGuard parity)
from . import sparse  # noqa: E402
from . import quantization  # noqa: E402
from . import audio  # noqa: E402
from . import text  # noqa: E402
from . import hub  # noqa: E402
from . import static  # noqa: E402
from . import version  # noqa: E402
from . import device  # noqa: E402
from . import geometric  # noqa: E402
from . import strings  # noqa: E402
from . import models  # noqa: E402
from . import serving  # noqa: E402
from . import onnx  # noqa: E402
from .hapi import Model  # noqa: E402  (paddle.Model parity)
from .hapi import callbacks  # noqa: E402  (paddle.callbacks parity)


def grad(func, argnums=0, has_aux=False):
    """Functional gradient (the TPU-native autograd entry; replaces the
    reference's eager GradNode engine, SURVEY §3.2 — jax.grad is the engine)."""
    import jax

    return jax.grad(func, argnums=argnums, has_aux=has_aux)
