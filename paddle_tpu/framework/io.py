"""paddle.save/load equivalents (parity: python/paddle/framework/io.py:723/960).

Format: a pickle of the nested object with jax/numpy arrays swapped for
numpy payloads — same shape as the reference's pickled state dicts, so
user code (`paddle.save(model.state_dict(), path)`) ports directly.
Distributed/sharded checkpointing lives in distributed.checkpoint.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import numpy as np

__all__ = ["save", "load"]

_PROTOCOL = 4


def _to_host(obj: Any):
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **kwargs) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_host(obj), f, protocol=protocol)


def load(path: str, **kwargs) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)
