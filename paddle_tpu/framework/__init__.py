"""Framework-level utilities: save/load, seeding re-export.
(parity: python/paddle/framework/)."""

from .io import load, save  # noqa: F401
from ..core.rng import seed  # noqa: F401
from ..core.dtypes import get_default_dtype, set_default_dtype  # noqa: F401
