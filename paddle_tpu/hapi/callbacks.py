"""Training callbacks (parity: python/paddle/hapi/callbacks.py —
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL; the
VisualDL writer becomes a CSV/JSONL history logger, TensorBoard being the
TPU-native visualizer via jax.profiler)."""

from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "HistoryLogger", "CallbackList"]


class Callback:
    """Base callback: hooks mirror hapi/callbacks.py:Callback."""

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            if model is not None:
                c.set_model(model)
            c.set_params(params or {})

    def _dispatch(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._dispatch(name, *a)
        raise AttributeError(name)

    @property
    def stop_training(self):
        return any(getattr(c, "stop_training", False) for c in self.callbacks)


class ProgBarLogger(Callback):
    """Parity: hapi ProgBarLogger — per-epoch progress with loss/metrics."""

    def __init__(self, log_freq: int = 1, verbose: int = 1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            ips = (step + 1) / max(time.time() - self._start, 1e-9)
            parts = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in logs.items()]
            total = self.steps if self.steps is not None else "?"
            print(f"step {step + 1}/{total} - " + " - ".join(parts)
                  + f" - {ips:.1f} step/s", file=sys.stdout)

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            parts = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in logs.items()]
            print("Eval - " + " - ".join(parts))


class ModelCheckpoint(Callback):
    """Parity: hapi ModelCheckpoint — saves weights every save_freq epochs."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Parity: hapi EarlyStopping (monitor/patience/min_delta/mode)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stop_training = False

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline if self.baseline is not None else (
            float("-inf") if self.mode == "max" else float("inf"))

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no {self.monitor} improvement "
                          f"in {self.patience} evals")


class LRScheduler(Callback):
    """Parity: hapi LRScheduler — steps the optimizer's lr schedule (our
    schedules are step-indexed functions, so this only controls by_step /
    by_epoch stepping granularity bookkeeping)."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch


class HistoryLogger(Callback):
    """JSONL metrics history (the VisualDL-writer slot)."""

    def __init__(self, path: str):
        self.path = path

    def on_train_begin(self, logs=None):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._f = open(self.path, "a")

    def on_epoch_end(self, epoch, logs=None):
        rec = {"epoch": epoch, **{k: (float(v) if hasattr(v, "__float__")
                                      else v) for k, v in (logs or {}).items()}}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def on_train_end(self, logs=None):
        self._f.close()
