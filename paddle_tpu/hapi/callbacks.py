"""Training callbacks (parity: python/paddle/hapi/callbacks.py —
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL; the
VisualDL writer becomes a CSV/JSONL history logger, TensorBoard being the
TPU-native visualizer via jax.profiler)."""

from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "HistoryLogger", "CallbackList",
           "ReduceLROnPlateau", "VisualDL", "WandbCallback"]


class Callback:
    """Base callback: hooks mirror hapi/callbacks.py:Callback."""

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            if model is not None:
                c.set_model(model)
            c.set_params(params or {})

    def _dispatch(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._dispatch(name, *a)
        raise AttributeError(name)

    @property
    def stop_training(self):
        return any(getattr(c, "stop_training", False) for c in self.callbacks)


class ProgBarLogger(Callback):
    """Parity: hapi ProgBarLogger — per-epoch progress with loss/metrics."""

    def __init__(self, log_freq: int = 1, verbose: int = 1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            ips = (step + 1) / max(time.time() - self._start, 1e-9)
            parts = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in logs.items()]
            total = self.steps if self.steps is not None else "?"
            print(f"step {step + 1}/{total} - " + " - ".join(parts)
                  + f" - {ips:.1f} step/s", file=sys.stdout)

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            parts = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in logs.items()]
            print("Eval - " + " - ".join(parts))


class ModelCheckpoint(Callback):
    """Parity: hapi ModelCheckpoint — saves weights every save_freq epochs."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Parity: hapi EarlyStopping (monitor/patience/min_delta/mode)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stop_training = False

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline if self.baseline is not None else (
            float("-inf") if self.mode == "max" else float("inf"))

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no {self.monitor} improvement "
                          f"in {self.patience} evals")


class LRScheduler(Callback):
    """Parity: hapi LRScheduler — steps the optimizer's lr schedule (our
    schedules are step-indexed functions, so this only controls by_step /
    by_epoch stepping granularity bookkeeping)."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch


class _JsonlWriter:
    """Shared lazy JSONL sink for the logging callbacks: opens on first
    write (so evaluate-only flows that skip on_train_begin still work),
    coerces scalars to float, flushes per record."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def write(self, **fields):
        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "a")
        rec = {k: (float(v) if hasattr(v, "__float__") else v)
               for k, v in fields.items()}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


class HistoryLogger(Callback):
    """JSONL metrics history."""

    def __init__(self, path: str):
        self._writer = _JsonlWriter(path)

    def on_epoch_end(self, epoch, logs=None):
        self._writer.write(epoch=epoch, **(logs or {}))

    def on_train_end(self, logs=None):
        self._writer.close()


from ..optimizer.lr import LRScheduler as _BaseSched  # noqa: E402


class _ScaledScheduler(_BaseSched):
    """An LRScheduler multiplying a base schedule by a running scale
    (ReduceLROnPlateau's composable reduction): warmup/decay keep their
    shape at a reduced amplitude. Subclasses LRScheduler so the
    optimizer's isinstance dispatch keeps treating it as a schedule."""

    def __init__(self, base, scale, min_lr):  # no super().__init__: the
        # base schedule owns last_epoch/last_lr bookkeeping
        self.base = base
        self.scale = float(scale)
        self.min_lr = float(min_lr)

    def lr_at(self, step):
        return max(float(self.base.lr_at(step)) * self.scale, self.min_lr)

    def get_lr(self):
        return max(float(self.base.get_lr()) * self.scale, self.min_lr)

    def step(self, epoch=None):
        self.base.step(epoch)

    @property
    def last_epoch(self):
        return self.base.last_epoch

    @property
    def last_lr(self):
        return max(float(self.base.last_lr) * self.scale, self.min_lr)

    def __call__(self, step):
        return self.lr_at(step)

    def state_dict(self):
        return {"scale": self.scale, **self.base.state_dict()}

    def set_state_dict(self, state):
        self.scale = state.pop("scale", self.scale)
        self.base.set_state_dict(state)


class ReduceLROnPlateau(Callback):
    """Parity: hapi ReduceLROnPlateau — scale the optimizer lr by
    ``factor`` after ``patience`` evals without improvement; composes
    with an existing LR schedule instead of replacing it."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.cooldown_counter = 0
        self.best = float("-inf") if self.mode == "max" else float("inf")

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        if not hasattr(self, "wait"):  # evaluate-only flow: lazy init
            self.on_train_begin()
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.cooldown_counter > 0:
            # inside the cooldown window: no reductions, no waiting
            self.cooldown_counter -= 1
            self.wait = 0
            if self._better(cur):
                self.best = cur
            return
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = self.model._optimizer
            old = float(opt.get_lr())
            self._reduce(opt)
            if self.verbose:
                print(f"ReduceLROnPlateau: lr {old:.3g} -> "
                      f"{float(opt.get_lr()):.3g}")
            self.cooldown_counter = self.cooldown
            self.wait = 0

    def _reduce(self, opt):
        from ..optimizer.lr import LRScheduler as _Sched
        lr = opt._lr
        if isinstance(lr, _ScaledScheduler):
            lr.scale *= self.factor  # last_lr is a property: auto-refreshes
        elif isinstance(lr, _Sched):
            # COMPOSE with the schedule (warmup/decay keep running at a
            # reduced amplitude) instead of stomping it to a constant
            opt._lr = _ScaledScheduler(lr, self.factor, self.min_lr)
        else:
            opt.set_lr(max(float(lr) * self.factor, self.min_lr))


class VisualDL(Callback):
    """Parity slot for hapi VisualDL. The visualdl package is not in
    this environment, so scalars land in a JSONL event file under
    ``log_dir`` (one record per epoch/eval, the same scalars VisualDL
    would chart); point any dashboard at it."""

    def __init__(self, log_dir="./log"):
        self._writer = _JsonlWriter(os.path.join(log_dir,
                                                 "vdl_scalars.jsonl"))

    def on_epoch_end(self, epoch, logs=None):
        self._writer.write(tag="train", step=epoch, **(logs or {}))

    def on_eval_end(self, logs=None):
        # each eval gets its own monotone step (the real VisualDL writer
        # keeps per-tag counters the same way)
        step = getattr(self, "_eval_count", 0)
        self._eval_count = step + 1
        self._writer.write(tag="eval", step=step, **(logs or {}))

    def on_train_end(self, logs=None):
        self._writer.close()


class WandbCallback(Callback):
    """Parity: hapi WandbCallback — requires the (optional) wandb
    package; raises with guidance when absent (no egress here anyway)."""

    def __init__(self, project=None, **kwargs):
        from ..utils import try_import
        self._wandb = try_import(
            "wandb", "WandbCallback needs the wandb package, which is not "
            "installed in this environment; use VisualDL/HistoryLogger "
            "(JSONL scalars) instead")
        self._init_kwargs = {"project": project, **kwargs}

    def on_train_begin(self, logs=None):
        self._run = self._wandb.init(**self._init_kwargs)

    def on_epoch_end(self, epoch, logs=None):
        self._wandb.log({k: v for k, v in (logs or {}).items()},
                        step=epoch)

    def on_train_end(self, logs=None):
        self._wandb.finish()
