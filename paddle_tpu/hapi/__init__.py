"""hapi — the Keras-like high-level API (parity: python/paddle/hapi/
model.py — Model.prepare/fit/evaluate/predict/save/load/summary :1750, and
callbacks.py).

TPU-native: fit() drives ONE compiled TrainStep (forward+backward+optimizer
in a single XLA program) instead of the reference's per-op dygraph loop;
evaluate/predict reuse the compiled EvalStep. Everything else — callbacks,
metrics, checkpointing — is the same orchestration surface.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from .. import optimizer as _opt
from ..framework.io import load as _load, save as _save
from ..jit import EvalStep, TrainStep
from ..metric import Metric
from ..nn.module import Layer
from . import callbacks as callbacks  # noqa: F401  (paddle.callbacks parity)
from .flops import flops, summary  # noqa: F401
from .callbacks import CallbackList, ProgBarLogger

__all__ = ["Model", "callbacks", "flops", "summary"]


class Model:
    """Parity: paddle.Model (hapi/model.py)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._train_step = None
        self._eval_step = None

    # ---- configuration ----

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        ms = metrics or []
        self._metrics = ms if isinstance(ms, (list, tuple)) else [ms]
        # TrainStep is built lazily on the first batch so n_inputs matches
        # the dataset arity (multi-input models get every input forwarded)
        self._eval_step = EvalStep(self.network)

    def _ensure_train_step(self, n_inputs: int):
        if self._train_step is None:
            if self._optimizer is None or self._loss is None:
                raise RuntimeError("call prepare(optimizer=..., loss=...) "
                                   "before fit()")
            self._train_step = TrainStep(
                self.network, self._optimizer,
                lambda out, *labels: self._loss(out, *labels),
                n_inputs=n_inputs)
        return self._train_step

    # ---- training ----

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        from ..io.dataloader import DataLoader
        loader = train_data
        if not isinstance(train_data, DataLoader):
            loader = DataLoader(train_data, batch_size=batch_size,
                                shuffle=shuffle, drop_last=drop_last,
                                num_workers=num_workers)
        eval_loader = eval_data
        if eval_data is not None and not isinstance(eval_data, DataLoader):
            eval_loader = DataLoader(eval_data, batch_size=batch_size)
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.insert(0, ProgBarLogger(log_freq=log_freq, verbose=verbose))
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cblist = CallbackList(cbs, model=self,
                              params={"epochs": epochs, "steps": steps,
                                      "verbose": verbose})
        cblist.on_train_begin()
        history = {"loss": []}
        for epoch in range(epochs):
            self.network.train()
            cblist.on_epoch_begin(epoch)
            last_loss = None
            for step, batch in enumerate(loader):
                cblist.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                loss = self._ensure_train_step(len(inputs))(*inputs, *labels)
                last_loss = float(loss)
                cblist.on_train_batch_end(step, {"loss": last_loss})
            logs = {"loss": last_loss}
            history["loss"].append(last_loss)
            if save_dir and epoch % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _callbacks=cblist)
                logs.update(eval_logs)
            cblist.on_epoch_end(epoch, logs)
            if cblist.stop_training:
                break
        cblist.on_train_end({"loss": last_loss})
        if save_dir:
            self.save(os.path.join(save_dir, "final"))
        return history

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[:-1], batch[-1:]
        return (batch,), ()

    # ---- evaluation / prediction ----

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _callbacks=None):
        from ..io.dataloader import DataLoader
        loader = eval_data
        if not isinstance(eval_data, DataLoader):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        self.network.eval()
        if self._eval_step is None:
            self._eval_step = EvalStep(self.network)
        for m in self._metrics:
            m.reset()
        cblist = _callbacks or CallbackList(list(callbacks or []), model=self,
                                            params={"verbose": verbose})
        cblist.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            inputs, labels = self._split_batch(batch)
            out = self._eval_step(*inputs)
            if self._loss is not None and labels:
                losses.append(float(self._loss(out, *labels)))
            for m in self._metrics:
                m.update(m.compute(out, *labels))
            cblist.on_eval_batch_end(step)
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
            logs["loss"] = logs["eval_loss"]  # EarlyStopping default monitor
        for m in self._metrics:
            res = m.accumulate()
            name = m.name() if callable(getattr(m, "name", None)) else str(m)
            if isinstance(name, (list, tuple)):
                for n, r in zip(name, np.atleast_1d(res)):
                    logs[n] = float(r)
            else:
                logs[name] = (float(res) if np.ndim(res) == 0
                              else float(np.asarray(res).ravel()[0]))
        cblist.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        from ..io.dataloader import DataLoader
        loader = test_data
        if not isinstance(test_data, DataLoader):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        self.network.eval()
        if self._eval_step is None:
            self._eval_step = EvalStep(self.network)
        outs = []
        for batch in loader:
            if isinstance(batch, (list, tuple)):
                # trailing element is the label for (x, ..., y) datasets;
                # single-element batches are pure inputs
                inputs = tuple(batch) if len(batch) == 1 else tuple(batch[:-1])
            else:
                inputs = (batch,)
            outs.append(np.asarray(self._eval_step(*inputs)))
        if stack_outputs:
            return [np.concatenate(outs, axis=0)]
        return [outs]

    # ---- persistence / introspection ----

    def save(self, path: str, training: bool = True):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._train_step is not None:
            _save(self._train_step.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer: bool = False):
        self.network.set_state_dict(_load(path + ".pdparams"))
        if not reset_optimizer and self._train_step is not None and \
                os.path.exists(path + ".pdopt"):
            self._train_step.set_state_dict(_load(path + ".pdopt"))

    def parameters(self):
        return list(self.network.param_dict().values())

    def summary(self, input_size=None, dtype=None):
        """Parity: hapi summary — parameter table + totals."""
        rows = []
        total = 0
        trainable = 0
        params = self.network.param_dict()
        train_set = set(self.network.param_dict(trainable_only=True))
        for k, v in params.items():
            n = int(np.prod(v.shape))
            total += n
            if k in train_set:
                trainable += n
            rows.append((k, tuple(v.shape), n))
        width = max((len(r[0]) for r in rows), default=20) + 2
        lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}"]
        lines += [f"{k:<{width}}{str(s):<20}{n:>12,}" for k, s, n in rows]
        lines.append(f"Total params: {total:,}")
        lines.append(f"Trainable params: {trainable:,}")
        print("\n".join(lines))
        return {"total_params": total, "trainable_params": trainable}
