"""Model statistics (parity: python/paddle/hapi/{dynamic_flops.py,
model_summary.py} — paddle.flops and the standalone paddle.summary).

FLOP counting uses the reference's per-layer formulas (one MAC = one
FLOP, conv = out_elems * (Cin/g * prod(k) [+1 bias]), linear =
in * out [+ out]); shapes come from forward hooks over a zeros forward,
so any composite model that runs, counts."""

from __future__ import annotations

import numpy as np

__all__ = ["flops", "summary"]


def _num_params(layer):
    return sum(int(np.prod(p.shape))
               for p in layer.parameters()) if hasattr(layer, "parameters") \
        else 0


def _layer_flops(layer, inputs, output):
    x = inputs[0] if isinstance(inputs, tuple) else inputs
    out = output[0] if isinstance(output, (tuple, list)) else output
    oshape = getattr(out, "shape", None)
    if oshape is None:
        return 0
    out_elems = int(np.prod(oshape))
    name = type(layer).__name__
    if name.startswith("Conv") and hasattr(layer, "kernel_size"):
        cin = layer.in_channels // max(getattr(layer, "groups", 1), 1)
        k = int(np.prod(layer.kernel_size))
        bias = 1 if getattr(layer, "bias", None) is not None else 0
        return out_elems * (cin * k + bias)
    if name == "Linear":
        batch = int(np.prod(oshape[:-1]))
        bias = layer.out_features if getattr(layer, "bias", None) is not None \
            else 0
        return batch * layer.in_features * layer.out_features + batch * bias
    if "Norm" in name:
        return 2 * int(np.prod(getattr(x, "shape", oshape)))
    if "Pool" in name or name in ("ReLU", "ReLU6", "GELU", "Sigmoid",
                                  "Tanh", "Hardswish", "Hardsigmoid",
                                  "Swish", "LeakyReLU", "Softmax", "SiLU"):
        return int(np.prod(getattr(x, "shape", oshape)))
    return 0


def _trace(net, input_size=None, dtypes=None, custom_ops=None, args=None):
    """Run one forward (zeros built from ``input_size`` or the given
    ``args``) with leaf hooks; returns rows of
    (name, type, out_shape, params, flops)."""
    import jax.numpy as jnp
    rows = []
    handles = []

    def make_hook(lname):
        def hook(layer, inputs, output):
            if layer._sub_layers:  # only leaves carry counts
                return None
            fn = None
            if custom_ops:
                fn = custom_ops.get(type(layer))
            fl = fn(layer, inputs, output) if fn \
                else _layer_flops(layer, inputs, output)
            out = output[0] if isinstance(output, (tuple, list)) else output
            rows.append((lname, type(layer).__name__,
                         tuple(getattr(out, "shape", ())),
                         _num_params(layer), int(fl)))
            return None
        return hook

    subs = list(net.named_sublayers())
    if not subs:  # a bare leaf layer IS the model
        subs = [(type(net).__name__.lower(), net)]
    for name, sub in subs:
        handles.append(sub.register_forward_post_hook(make_hook(name)))
    try:
        if args is None:
            sizes = input_size if isinstance(input_size, (list, tuple)) and \
                input_size and isinstance(input_size[0], (list, tuple)) \
                else [input_size]
            dts = dtypes or ["float32"] * len(sizes)
            args = [jnp.zeros(tuple(s), dt) for s, dt in zip(sizes, dts)]
        net(*args)
    finally:
        for h in handles:
            h.remove()
    return rows


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Parity: paddle.flops (hapi/dynamic_flops.py). Returns total FLOPs
    of one forward at ``input_size``; ``custom_ops`` maps layer TYPES to
    ``fn(layer, inputs, output) -> flops``."""
    rows = _trace(net, input_size, custom_ops=custom_ops)
    total = sum(r[4] for r in rows)
    if print_detail:
        width = max(max((len(r[0]) for r in rows), default=10) + 2, 14)
        print(f"{'Layer':<{width}}{'Type':<18}{'Output shape':<22}"
              f"{'Params':>10}{'FLOPs':>14}")
        for name, typ, shape, n, fl in rows:
            print(f"{name:<{width}}{typ:<18}{str(shape):<22}"
                  f"{n:>10,}{fl:>14,}")
        print(f"Total FLOPs: {total:,}")
    return total


def summary(net, input_size=None, dtypes=None, input=None):
    """Parity: paddle.summary (hapi/model_summary.py) — per-layer table
    with output shapes + parameter totals; returns the totals dict."""
    if input is not None:
        rows = _trace(net, args=input if isinstance(input, (list, tuple))
                      else (input,))
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        rows = _trace(net, input_size, dtypes=dtypes)
    params = net.param_dict()
    total = sum(int(np.prod(v.shape)) for v in params.values())
    trainable = sum(int(np.prod(v.shape))
                    for v in net.param_dict(trainable_only=True).values())
    width = max(max((len(r[0]) + len(r[1]) for r in rows), default=10) + 5, 24)
    lines = [f"{'Layer (type)':<{width}}{'Output Shape':<24}{'Param #':>12}"]
    lines += [f"{(n + ' (' + t + ')'):<{width}}{str(s):<24}{p:>12,}"
              for n, t, s, p, _ in rows]
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
