"""DLPack interop (parity: python/paddle/utils/dlpack.py —
to_dlpack/from_dlpack). JAX arrays speak DLPack natively, so this is a
zero-copy bridge to torch/numpy/cupy on the same device."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a tensor as a DLPack capsule (zero-copy where possible)."""
    x = jnp.asarray(x)
    return x.__dlpack__()


def from_dlpack(dlpack):
    """Import any object implementing the DLPack protocol (``__dlpack__``
    + ``__dlpack_device__``: torch/cupy/numpy/jax arrays) as a framework
    tensor, zero-copy on the same device.

    Deviation from the reference: bare PyCapsules are rejected — a
    capsule carries no device information, so importing one would have
    to GUESS where the memory lives (XLA refuses them for the same
    reason). Pass the producing array object instead; every current
    framework exposes the protocol."""
    if hasattr(dlpack, "__dlpack__") and hasattr(dlpack, "__dlpack_device__"):
        return jnp.from_dlpack(dlpack)
    raise TypeError(
        "from_dlpack needs an object with __dlpack__/__dlpack_device__ "
        "(e.g. the torch/cupy/numpy array itself, not a raw capsule — "
        "a capsule cannot say which device its memory is on)")
