"""Deprecation + optional-import helpers (parity: python/paddle/utils/
{deprecated,lazy_import}.py)."""

from __future__ import annotations

import functools
import importlib
import warnings

__all__ = ["deprecated", "try_import"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 1):
    """Mark an API deprecated: warns (level 1) or raises (level 2)."""

    def deco(fn):
        msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f"; use {update_to} instead"
        if reason:
            msg += f" ({reason})"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        wrapper.__doc__ = (f"[DEPRECATED] {msg}\n\n" + (fn.__doc__ or ""))
        return wrapper

    return deco


def try_import(module_name: str, err_msg: str | None = None):
    """Import an optional dependency with an actionable error."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"optional dependency {module_name!r} is not "
            f"installed (and this environment cannot pip install — gate "
            f"the feature)") from e
