"""paddle_tpu.utils (parity: python/paddle/utils/ — the custom-op toolchain
lives in utils.cpp_extension in the reference; here in utils.custom_op)."""

from . import cpp_extension  # noqa: F401
from . import custom_op  # noqa: F401
from . import dlpack  # noqa: F401
from . import unique_name  # noqa: F401
from .deprecated import deprecated, try_import  # noqa: F401
