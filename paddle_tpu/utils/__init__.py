"""paddle_tpu.utils (parity: python/paddle/utils/ — the custom-op toolchain
lives in utils.cpp_extension in the reference; here in utils.custom_op)."""

from . import custom_op  # noqa: F401
from . import cpp_extension  # noqa: F401
