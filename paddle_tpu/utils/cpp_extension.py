"""cpp_extension compatibility surface (parity:
python/paddle/utils/cpp_extension/cpp_extension.py — ``setup`` :79,
``load`` :795, ``CppExtension``/``CUDAExtension``).

On TPU the out-of-tree kernel language is Pallas, not C++/CUDA — the
equivalent toolchain is :mod:`paddle_tpu.utils.custom_op`. This module keeps
the reference's entry-point names so ported build scripts fail with an
actionable message instead of an AttributeError, and supports the one case
where native code IS still the answer on TPU hosts: building a plain CPU
C++ extension (data loading / tokenization fast paths) with setuptools.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import tempfile

__all__ = ["setup", "load", "CppExtension", "CUDAExtension", "load_inline"]

_PALLAS_MSG = (
    "TPU kernels are written in Pallas, not {kind}: register them with "
    "paddle_tpu.utils.custom_op.register_custom_op (custom VJP + sharding "
    "rule + contract-test enrollment). cpp_extension.{fn} only builds "
    "host-CPU helper extensions."
)


def CppExtension(sources, *args, **kwargs):
    return {"kind": "cpp", "sources": sources, "kwargs": kwargs}


def CUDAExtension(sources, *args, **kwargs):
    raise RuntimeError(_PALLAS_MSG.format(kind="CUDA", fn="CUDAExtension"))


def setup(**attrs):
    raise RuntimeError(_PALLAS_MSG.format(kind="C++/CUDA", fn="setup"))


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False, **kwargs):
    """JIT-build a host-CPU shared library from C++ sources and dlopen it
    via ctypes (the reference's jit ``load`` :795, minus CUDA). Returns the
    ctypes CDLL — symbol access is the caller's contract."""
    import ctypes

    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), f"paddle_tpu_ext_{name}")
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(build_dir, f"{name}.so")
    srcs = [sources] if isinstance(sources, str) else list(sources)
    if (not os.path.exists(so_path)
            or any(os.path.getmtime(s) > os.path.getmtime(so_path)
                   for s in srcs if os.path.exists(s))):
        # compile to a per-pid temp and atomically rename: N processes may
        # race on the first build (multiprocess DataLoader workers) and must
        # never dlopen a partially written .so
        tmp_so = f"{so_path}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
               f"-I{sysconfig.get_paths()['include']}",
               *(extra_cxx_cflags or []), *srcs, "-o", tmp_so]
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True)
        os.replace(tmp_so, so_path)
    return ctypes.CDLL(so_path)


def load_inline(name, cpp_source, functions=None, **kwargs):
    """Build from an inline C++ source string (torch-style convenience).
    The source file is only rewritten when its content changed, so the
    compiled .so stays cached across processes (multiprocess DataLoader
    workers must not each trigger a rebuild/race)."""
    build_dir = os.path.join(tempfile.gettempdir(),
                             f"paddle_tpu_ext_{name}_src")
    os.makedirs(build_dir, exist_ok=True)
    src = os.path.join(build_dir, f"{name}.cc")
    existing = None
    if os.path.exists(src):
        with open(src) as f:
            existing = f.read()
    if existing != cpp_source:
        with open(src, "w") as f:
            f.write(cpp_source)
    return load(name, [src], build_directory=build_dir, **kwargs)
