"""Custom-op registration — the TPU-native cpp_extension (parity:
python/paddle/utils/cpp_extension/cpp_extension.py:79 ``setup``/``load`` +
``PD_BUILD_OP`` op_meta_info.h:1150 + fluid/framework/custom_operator.cc).

The reference compiles user C++/CUDA against installed headers and registers
the result as a first-class op (dygraph + static + inference). On TPU the
"kernel language" is Pallas (or any jax-traceable callable), so the
toolchain collapses to ONE registration call that wires up everything the
C++ macro stack did:

- **autograd**: a custom VJP (``bwd``) installed via jax.custom_vjp;
- **sharding rule**: the SPMD rule (``sharding_rule``) — the analogue of a
  phi/infermeta/spmd_rules entry — applied by wrapping the kernel in
  shard_map when a mesh is active, so the op composes with dp/tp/fsdp
  programs instead of falling off the GSPMD propagation path;
- **contract enrollment**: a numpy reference + input generator auto-enrolls
  the op in the OpTest-style contract suite (tests/test_op_contract.py);
- **inventory**: the op appears in ``core.registry.all_ops()``.

Example — a fused scale-and-shift op with a hand-written backward::

    import jax.numpy as jnp
    from paddle_tpu.utils.custom_op import register_custom_op

    def sscale_fwd(x, alpha):
        return jnp.tanh(x) * alpha

    def sscale_bwd(residuals, g):
        x, alpha = residuals
        t = jnp.tanh(x)
        return g * alpha * (1 - t * t), jnp.sum(g * t)

    sscale = register_custom_op(
        "sscale", sscale_fwd, bwd=sscale_bwd,
        ref=lambda x, a: np.tanh(x) * a,
        make_inputs=lambda rng: (rng.standard_normal((4, 8)).astype("float32"),
                                 np.float32(1.7)),
        grad_ref=True,
        sharding_rule=lambda mesh, x, a: (((P("dp"), None), P("dp"))
                                          if "dp" in mesh.axis_names else None))

The returned callable is the public op; the contract suite picks it up on
the next run with zero extra test code.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
from jax.sharding import PartitionSpec as P  # noqa: F401  (docstring example)
from ..core.compat import shard_map
from ..core.registry import register_contract
from ..core import mesh as mesh_lib

__all__ = ["register_custom_op", "CustomOpBuilder"]


def register_custom_op(
    name: str,
    fwd: Callable,
    *,
    bwd: Callable | None = None,
    fwd_res: Callable | None = None,
    ref: Callable | None = None,
    make_inputs: Callable | None = None,
    grad_ref: bool = False,
    sharding_rule: Callable | None = None,
    notes: str = "",
) -> Callable:
    """Register ``fwd`` as a first-class custom op.

    Args:
      fwd: the kernel — a Pallas call or any jax-traceable function.
      bwd: custom backward ``bwd(residuals, cotangent) -> grads`` (one per
        positional input). Default residuals are the primal inputs; pass
        ``fwd_res(out, *inputs) -> residuals`` to save something else
        (e.g. the flash-attention LSE).
      ref / make_inputs / grad_ref: OpTest contract hooks — numpy reference,
        input generator, and whether jax.grad is finite-difference checked.
      sharding_rule: ``rule(mesh, *inputs) -> (in_specs, out_specs) | None``
        — when a mesh is active and the rule returns specs, the kernel runs
        under shard_map with them (SPMD-rule parity for kernels GSPMD cannot
        see through).
    """
    kernel = fwd
    if bwd is not None:
        @jax.custom_vjp
        def op_core(*args):
            return kernel(*args)

        def op_fwd(*args):
            out = kernel(*args)
            res = fwd_res(out, *args) if fwd_res is not None else args
            return out, res

        def op_bwd(res, g):
            grads = bwd(res, g)
            return grads if isinstance(grads, tuple) else (grads,)

        op_core.defvjp(op_fwd, op_bwd)
    else:
        op_core = kernel

    @functools.wraps(fwd)
    def op(*args, **kwargs):
        mesh = mesh_lib.current_mesh()
        if sharding_rule is not None and mesh is not None and \
                any(s > 1 for s in mesh.shape.values()):
            specs = sharding_rule(mesh, *args)
            if specs is not None:
                in_specs, out_specs = specs
                return jax.jit(shard_map(
                    lambda *a: op_core(*a, **kwargs), mesh=mesh,
                    in_specs=tuple(in_specs), out_specs=out_specs,
                    check_vma=False))(*args)
        return op_core(*args, **kwargs)

    op.__name__ = name
    register_contract(name, op, ref, make_inputs, fn_call=op,
                      grad_ref=grad_ref, category="custom",
                      notes=notes or "custom op (register_custom_op)")
    return op


class CustomOpBuilder:
    """Fluent variant mirroring the PD_BUILD_OP macro chain::

        op = (CustomOpBuilder("my_op")
              .forward(fwd).backward(bwd)
              .reference(np_ref, make_inputs)
              .sharding(rule).build())
    """

    def __init__(self, name: str):
        self._name = name
        self._kw = {}
        self._fwd = None

    def forward(self, fn):
        self._fwd = fn
        return self

    def backward(self, fn, fwd_res=None):
        self._kw["bwd"] = fn
        if fwd_res is not None:
            self._kw["fwd_res"] = fwd_res
        return self

    def reference(self, ref, make_inputs=None, grad_ref=False):
        self._kw.update(ref=ref, make_inputs=make_inputs, grad_ref=grad_ref)
        return self

    def sharding(self, rule):
        self._kw["sharding_rule"] = rule
        return self

    def build(self):
        if self._fwd is None:
            raise ValueError("forward kernel not set")
        return register_custom_op(self._name, self._fwd, **self._kw)
