"""Unique name generation (parity: python/paddle/utils/unique_name.py —
generate/guard/switch over a process-wide counter namespace)."""

from __future__ import annotations

import contextlib
import threading

__all__ = ["generate", "guard", "switch"]

_lock = threading.Lock()


class _Generator:
    def __init__(self):
        self.ids: dict[str, int] = {}

    def __call__(self, key: str) -> str:
        with _lock:
            n = self.ids.get(key, 0)
            self.ids[key] = n + 1
        return f"{key}_{n}"


_generator = _Generator()


def generate(key: str) -> str:
    """Next unique name for ``key``: key_0, key_1, ..."""
    return _generator(key)


def switch(new_generator=None):
    """Replace the active namespace; returns the previous one."""
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None else _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh (or given) namespace; restores the old one on exit."""
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
