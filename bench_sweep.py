"""Bench config sweep (dev tool, not the driver's bench.py): measures step
time for several remat/batch configurations on the real chip to pick the
honest best for bench.py."""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def run_one(batch, seq, recompute, policy, interval=1, iters=6):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=seq, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None, recompute=recompute,
                      recompute_policy=policy, recompute_interval=interval)
    model = LlamaForCausalLM(cfg)
    n_params = model.num_params()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda logits, labels: model.loss(logits, labels))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    _ = float(step(ids, ids))
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    _ = float(loss)
    dt = (time.perf_counter() - t0) / iters
    tokens_per_sec = batch * seq / dt
    mfu = 6.0 * n_params * tokens_per_sec / 197e12
    return dict(batch=batch, seq=seq, recompute=recompute, policy=policy,
                step_ms=round(dt * 1000, 1),
                tokens_per_sec=round(tokens_per_sec, 0), mfu=round(mfu, 4))


def main():
    spec = sys.argv[1] if len(sys.argv) > 1 else "all"
    combos = {
        "base": (8, 2048, True, "full"),
        "dots": (8, 2048, True, "dots"),
        "noremat": (8, 2048, False, "full"),
        "b16dots": (16, 2048, True, "dots"),
        "b16": (16, 2048, True, "full"),
        "int2": (8, 2048, True, "full", 2),
        "int4": (8, 2048, True, "full", 4),
        "b4nore": (4, 2048, False, "full"),
        "b12": (12, 2048, True, "full"),
        "b6nore": (6, 2048, False, "full"),
        "b5nore": (5, 2048, False, "full"),
    }
    picks = combos.keys() if spec == "all" else spec.split(",")
    for name in picks:
        try:
            print(name, json.dumps(run_one(*combos[name])), flush=True)
        except Exception as e:  # OOM etc.
            print(name, "FAILED:", type(e).__name__, str(e)[:200], flush=True)


if __name__ == "__main__":
    main()
# extra combos appended during tuning
